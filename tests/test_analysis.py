"""tpu-lint (client_tpu/analysis): each rule proven against the real bug
it encodes — hit on the known-violation fixture, silent on the clean
twin — plus suppression comments, the baseline ratchet, the CLI gate,
and the requirement that the repo's own tree scans clean."""

import ast
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

from client_tpu.analysis import (
    PROGRAM_REGISTRY,
    REGISTRY,
    all_rules,
    scan_paths,
    scan_source,
)
from client_tpu.analysis import baseline as baseline_mod
from client_tpu.analysis import cache as cache_mod
from client_tpu.analysis import callgraph
from client_tpu.analysis.baseline import filter_findings
from client_tpu.analysis.witness import (
    LockOrderViolation,
    LockWitness,
)

FIXTURES = Path(__file__).parent / "analysis_fixtures"
ROOT = Path(__file__).parent.parent


def _scan(name):
    path = FIXTURES / name
    return scan_source(path.read_text(), str(path))


def _rules_hit(findings):
    return sorted({f.rule for f in findings})


def test_registry_has_all_rules():
    assert set(REGISTRY) >= {
        "NPY-TRUTH", "ASYNC-BLOCK", "LOCK-DISPATCH", "QUEUE-SENTINEL",
        "CV-WAIT-LOOP", "SHARED-MUT", "TIME-WALL", "METRIC-LABEL",
        "RESP-PARAM-OVERWRITE", "BARE-SUPPRESS", "STALE-SUPPRESS",
        "JIT-UNBOUNDED-SHAPE", "REFCOUNT-PAIR", "ACK-BEFORE-STORE",
    }
    assert set(PROGRAM_REGISTRY) >= {
        "LOCK-INV", "BLOCK-UNDER-LOCK", "CALLBACK-UNDER-LOCK",
        "PEER-CALL-UNDER-LOCK", "LOCKSET-RACE",
        "RESOURCE-LEAK", "DOUBLE-RELEASE", "USE-AFTER-RELEASE",
    }
    assert len(all_rules()) >= 18
    for rule in all_rules().values():
        assert rule.rationale  # every rule documents its motivating bug


# -- per-rule hits and misses ---------------------------------------------

def test_npy_truth_hits():
    findings = _scan("npy_truth_bad.py")
    assert _rules_hit(findings) == ["NPY-TRUTH"]
    # membership, remove, if-truthiness, bool(), while-not, assert, plus
    # the cross-method a2654c4 cancel() shape (membership + remove over a
    # numpy-bearing self-attribute, taint visible only in submit)
    assert len(findings) == 8
    cancel_hits = [f for f in findings if "self._pending" in f.message]
    assert len(cancel_hits) >= 2


def test_npy_truth_clean():
    assert _scan("npy_truth_ok.py") == []


def test_async_block_hits():
    findings = _scan("async_block_bad.py")
    assert _rules_hit(findings) == ["ASYNC-BLOCK"]
    # time.sleep, requests.get, self-queue get, local q.get, and the
    # bounded positional block=True put (unbounded puts never block)
    assert len(findings) == 5


def test_async_block_clean():
    assert _scan("async_block_ok.py") == []


def test_lock_dispatch_hits_prefix_admit():
    """The rule is proven against the real pre-fix _admit_locked: both
    jit dispatches under the *_locked convention plus the inline
    with-self._cv tick."""
    findings = _scan("prefix_admit_lock_dispatch.py")
    assert _rules_hit(findings) == ["LOCK-DISPATCH"]
    assert len(findings) == 3
    messages = " ".join(f.message for f in findings)
    assert "self._prefill" in messages
    assert "self._adopt" in messages
    assert "self._tick" in messages


def test_lock_dispatch_clean():
    assert _scan("lock_dispatch_ok.py") == []


def test_queue_sentinel_hits_prefix_cancel():
    """The rule is proven against the real pre-fix cancel(): the
    active-slot branch deactivates without closing the stream queue; the
    release-all path (put in the same branch) stays clean."""
    findings = _scan("prefix_cancel_queue_sentinel.py")
    assert _rules_hit(findings) == ["QUEUE-SENTINEL"]
    assert len(findings) == 1
    assert "slot.active = False" in findings[0].snippet


def test_queue_sentinel_clean():
    assert _scan("queue_sentinel_ok.py") == []


def test_cv_wait_loop_hits():
    findings = _scan("cv_wait_bad.py")
    assert _rules_hit(findings) == ["CV-WAIT-LOOP"]
    assert len(findings) == 1


def test_cv_wait_loop_clean():
    assert _scan("cv_wait_ok.py") == []


def test_shared_mut_hits():
    findings = _scan("shared_mut_bad.py")
    assert _rules_hit(findings) == ["SHARED-MUT"]
    assert len(findings) == 1
    assert "_backlog" in findings[0].message


def test_shared_mut_clean():
    assert _scan("shared_mut_ok.py") == []


def test_shared_mut_pool_hits():
    """Balancer-motivated shape: endpoint-pool health state written from
    request-side methods while the prober thread reads it."""
    findings = _scan("shared_mut_pool_bad.py")
    assert _rules_hit(findings) == ["SHARED-MUT"]
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "_states" in messages and "_draining" in messages


def test_shared_mut_pool_clean():
    assert _scan("shared_mut_pool_ok.py") == []


def test_shared_mut_discovery_hits():
    """Discovery-motivated shape: pool membership mutated IN PLACE
    (append/remove) outside the pool lock while the prober thread
    iterates it — the rule's in-place-mutator extension."""
    findings = _scan("shared_mut_discovery_bad.py")
    assert _rules_hit(findings) == ["SHARED-MUT"]
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "append" in messages and "remove" in messages
    assert "_endpoints" in messages


def test_shared_mut_discovery_clean():
    assert _scan("shared_mut_discovery_ok.py") == []


def test_resp_param_overwrite_hits():
    findings = _scan("resp_param_overwrite_bad.py")
    assert _rules_hit(findings) == ["RESP-PARAM-OVERWRITE"]
    # the subscript-chain stamp (rendered[0]) and the bare-name stamp on
    # a caller-owned response
    assert len(findings) == 2


def test_resp_param_overwrite_clean():
    assert _scan("resp_param_overwrite_ok.py") == []


def test_jit_unbounded_shape_hits():
    """The per-prompt-length prefill recompile shape (pre-serve/lm
    continuous.py): a jitted callable fed a ragged-reshaped request
    array with no pad/bucket sanitizer on the path."""
    findings = _scan("jit_unbounded_shape_bad.py")
    assert _rules_hit(findings) == ["JIT-UNBOUNDED-SHAPE"]
    assert len(findings) == 2  # plain ragged + sanitize-then-re-taint
    assert "pad/bucket" in findings[0].message


def test_jit_unbounded_shape_clean():
    """pad_prompt on the assignment path, inline in the argument list,
    AND rebinding the name to the sanitizer after a ragged reshape
    (last assignment wins) all fix the dispatch shape — no finding."""
    assert _scan("jit_unbounded_shape_ok.py") == []


def test_refcount_pair_hits():
    """The leaked-shared-block shape (serve/lm/kv.py discipline): a class
    that increments a refs/refcount attribute with no decrement anywhere
    — on a mapping (+=) and on a scalar (x = x + 1 rebind)."""
    findings = _scan("refcount_pair_bad.py")
    assert _rules_hit(findings) == ["REFCOUNT-PAIR"]
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "retain()" in messages and "acquire()" in messages
    assert "leaked reference" in findings[0].message


def test_refcount_pair_clean():
    """retain paired with release (the kv.py shape: AugAssign up, BinOp
    subtraction down) and non-refcount counters both stay silent."""
    assert _scan("refcount_pair_ok.py") == []


def test_bg_thread_crash_hits():
    """The silently-dying background thread (the endpoint-pool prober
    incident shape): a Thread-registered service loop whose body can
    raise with no top-level guard — method target AND bare-name target."""
    findings = _scan("bg_thread_crash_bad.py")
    assert _rules_hit(findings) == ["BG-THREAD-CRASH"]
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "_probe_loop()" in messages and "serve_forever()" in messages
    assert "kills the thread silently" in findings[0].message


def test_bg_thread_crash_clean():
    """Guarded shapes stay silent: whole-body try, loop under an outer
    try, the stop.wait sleep shape, bounded for-drivers, loop-less
    one-shot workers."""
    assert _scan("bg_thread_crash_ok.py") == []


def test_span_leak_hits():
    """The leaked-span shapes (the tracing brackets' invariant): a
    sampled span completed on the happy path only, a started timer
    never finished at all, and a profiler tick whose finish sits on
    the happy path only."""
    findings = _scan("span_leak_bad.py")
    assert _rules_hit(findings) == ["SPAN-LEAK"]
    assert len(findings) == 3
    messages = " ".join(f.message for f in findings)
    assert "outside any finally" in messages
    assert "never finishes" in messages
    assert "ptick" in messages


def test_span_leak_clean():
    """try/finally completion, the context-manager form, and both
    ownership transfers (returned / handed to a callee) stay silent."""
    assert _scan("span_leak_ok.py") == []


def test_ack_before_store_hits():
    """Peer replies counted as durability acks without consulting the
    reply's 'stored' field — both the assigned-reply and the
    for-loop-over-_ask shapes (the write-quorum lane's acks-then-loses
    fork)."""
    findings = _scan("ack_before_store_bad.py")
    assert _rules_hit(findings) == ["ACK-BEFORE-STORE"]
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "'stored'" in messages
    assert "reachable" in messages


def test_ack_before_store_clean():
    """'stored'-gated ack counting, transport delivery under a non-ack
    name, and ack bookkeeping with no peer reply in scope all stay
    silent."""
    assert _scan("ack_before_store_ok.py") == []


def test_time_wall_hits():
    findings = _scan("time_wall_bad.py")
    assert _rules_hit(findings) == ["TIME-WALL"]
    # the wall-clock deadline assignment, its comparison, the
    # attribute-expiry assignment, and the annotated-assignment form
    assert len(findings) == 4


def test_time_wall_clean():
    # monotonic deadlines and wall-clock *timestamps* both scan clean
    assert _scan("time_wall_ok.py") == []


def test_metric_label_hits():
    """The rule is proven against the pre-fix serve/metrics.py shape:
    model/version/device names interpolated into label positions without
    the escape helper."""
    findings = _scan("metric_label_bad.py")
    assert _rules_hit(findings) == ["METRIC-LABEL"]
    # one per offending line (core reports one finding per rule+line):
    # the model/version labels f-string and the device-id one
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "model" in messages and "device_id" in messages


def test_metric_label_clean():
    # escape_label()-wrapped label values and non-label interpolations
    # (sample values, metric name suffixes) both scan clean
    assert _scan("metric_label_ok.py") == []


def test_current_metrics_module_passes_metric_label():
    """The post-fix metrics renderer is the motivating module: every label
    value goes through escape_label()."""
    assert scan_paths(
        [str(ROOT / "client_tpu" / "serve" / "metrics.py")]
    ) == []


def test_current_continuous_passes_every_rule():
    """The post-fix scheduler is the motivating module: it must scan
    clean (cancel closes active queues; prefill dispatch left the lock)."""
    assert scan_paths(
        [str(ROOT / "client_tpu" / "serve" / "models" / "continuous.py")]
    ) == []


# -- suppression ----------------------------------------------------------

def test_suppression_comments():
    assert _scan("suppressed_ok.py") == []


def test_suppression_is_per_rule():
    src = (FIXTURES / "cv_wait_bad.py").read_text()
    # waiving a DIFFERENT rule must not silence the finding
    src = src.replace(
        "self._cv.wait()",
        "self._cv.wait()  # tpulint: disable=NPY-TRUTH -- wrong rule",
    )
    findings = scan_source(src, "cv_wait_bad.py")
    assert _rules_hit(findings) == ["CV-WAIT-LOOP"]


def test_parse_error_is_reported():
    findings = scan_source("def broken(:\n", "broken.py")
    assert _rules_hit(findings) == ["PARSE-ERROR"]


# -- baseline ratchet -----------------------------------------------------

def test_baseline_ratchet(tmp_path):
    findings = _scan("prefix_cancel_queue_sentinel.py")
    assert findings
    baseline_path = tmp_path / "baseline.json"
    baseline_mod.save(str(baseline_path), findings)
    counter = baseline_mod.load(str(baseline_path))

    # grandfathered finding passes
    new, old = filter_findings(findings, counter)
    assert new == [] and len(old) == len(findings)

    # a finding NOT in the baseline fails
    extra = _scan("cv_wait_bad.py")
    new, old = filter_findings(findings + extra, counter)
    assert [f.rule for f in new] == ["CV-WAIT-LOOP"]

    # the ratchet never grows: a second occurrence of a baselined line
    # beyond its recorded count is new
    new, old = filter_findings(findings + findings, counter)
    assert len(new) == len(findings) and len(old) == len(findings)


def test_committed_baseline_loads():
    counter = baseline_mod.load(baseline_mod.DEFAULT_BASELINE)
    assert sum(counter.values()) >= 0  # well-formed (possibly empty)


# -- CLI gate -------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "client_tpu.analysis", *args],
        cwd=str(ROOT), capture_output=True, text=True, timeout=120,
    )


def test_cli_exits_nonzero_on_findings():
    proc = _cli(
        "tests/analysis_fixtures/prefix_cancel_queue_sentinel.py",
        "tests/analysis_fixtures/prefix_admit_lock_dispatch.py",
        "--no-baseline",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "QUEUE-SENTINEL" in proc.stdout
    assert "LOCK-DISPATCH" in proc.stdout


def test_cli_repo_tree_is_clean():
    """The acceptance gate: the post-fix tree (sources AND tests) holds
    every invariant the rules encode."""
    proc = _cli("client_tpu", "tests")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_output():
    proc = _cli(
        "tests/analysis_fixtures/cv_wait_bad.py", "--json", "--no-baseline"
    )
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["count"] == 1
    assert payload["findings"][0]["rule"] == "CV-WAIT-LOOP"
    assert "CV-WAIT-LOOP" in payload["rules"]


def test_cli_rule_selection_and_catalog():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in REGISTRY:
        assert rule_id in proc.stdout
    # selecting only an unrelated rule silences the cv finding
    proc = _cli(
        "tests/analysis_fixtures/cv_wait_bad.py", "--rules", "NPY-TRUTH",
        "--no-baseline",
    )
    assert proc.returncode == 0
    proc = _cli("--rules", "NOT-A-RULE")
    assert proc.returncode == 2


def test_cli_missing_path_is_an_error():
    """A typo'd path must fail loudly (exit 2), not scan nothing and
    report a green gate."""
    proc = _cli("no_such_dir_anywhere", "--no-baseline")
    assert proc.returncode == 2
    assert "no such path" in proc.stderr


def test_fixtures_are_excluded_from_tree_scans():
    findings = scan_paths([str(Path("tests"))])
    assert all("analysis_fixtures" not in f.path for f in findings)


def test_write_baseline_rejects_filtered_scans():
    """A --rules- or path-filtered scan must not regenerate the baseline:
    it would silently drop every other rule's grandfathered entries."""
    proc = _cli("client_tpu", "--write-baseline")
    assert proc.returncode == 2
    proc = _cli("--rules", "NPY-TRUTH", "--write-baseline")
    assert proc.returncode == 2


def test_explicitly_named_excluded_dir_is_scanned():
    """Exclusion guards tree walks only: naming the fixtures dir directly
    must scan it (findings, exit 1), not report a silent green no-op."""
    proc = _cli("tests/analysis_fixtures", "--no-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "QUEUE-SENTINEL" in proc.stdout


# -- whole-program analysis (callgraph + concurrency rules) ----------------

def _pscan(*names):
    """Run per-file AND program rules over the named fixtures."""
    return scan_paths([str(FIXTURES / n) for n in names])


def test_block_under_lock_hits_interprocedural_prefill():
    """The prefill-under-_cv regression, one refactor past what the
    lexical rule can see: the dispatch is two calls below the ``with``.
    LOCK-DISPATCH must MISS it (that is the point of the fixture) and the
    call-graph pass must catch it, plus direct and one-call-deep host
    blocking under the lock."""
    findings = _pscan("block_under_lock_bad.py")
    assert _rules_hit(findings) == ["BLOCK-UNDER-LOCK"]
    assert len(findings) == 3
    messages = " ".join(f.message for f in findings)
    assert "self._prefill" in messages  # the jit dispatch, via the chain
    assert "_admit_one" in messages and "_do_prefill" in messages
    assert "time.sleep" in messages
    # the old lexical rule alone stays silent on this file
    lexical = scan_source(
        (FIXTURES / "block_under_lock_bad.py").read_text(),
        str(FIXTURES / "block_under_lock_bad.py"),
    )
    assert "LOCK-DISPATCH" not in _rules_hit(lexical)


def test_block_under_lock_clean():
    """The post-fix shape (pop under the lock, dispatch outside; cv.wait
    under its own lock) scans clean through every rule family."""
    assert _pscan("block_under_lock_ok.py") == []


def test_peer_call_under_lock_hits_fleet_shapes():
    """The fleet-tier stall: a peer RPC (timeout-bounded, so no blocking
    classifier fires) reached under an engine/pool lock — direct, one
    call below the ``with``, and a rendezvous collective under a pool
    lock.  The blocking rules must stay silent (that is the gap this
    rule closes)."""
    findings = _pscan("peer_call_under_lock_bad.py")
    assert _rules_hit(findings) == ["PEER-CALL-UNDER-LOCK"]
    assert len(findings) == 3
    messages = " ".join(f.message for f in findings)
    assert "prefix_lookup" in messages       # direct, under _cv
    assert "_fetch_remote" in messages       # through the call chain
    assert "cache_lookup" in messages
    assert "all_gather" in messages          # rendezvous collective


def test_peer_call_under_lock_clean():
    """The post-fix shape (snapshot under the lock, peer call outside —
    the serve/lm/engine.py submit/export structure) scans clean through
    every rule family."""
    assert _pscan("peer_call_under_lock_ok.py") == []


def test_lock_inv_hits_abba():
    findings = _pscan("lock_inv_bad.py")
    assert _rules_hit(findings) == ["LOCK-INV"]
    assert len(findings) == 1
    msg = findings[0].message
    assert "Ledger._audit_lock" in msg and "Ledger._write_lock" in msg
    # both witness edges are named, including the one hidden in a call
    assert "Ledger.credit" in msg and "Ledger._audit" in msg


def test_lock_inv_clean():
    assert _pscan("lock_inv_ok.py") == []


def test_callback_under_lock_hits_prefix_delivery():
    """Proven against the pre-fix pool/breaker delivery shape this PR
    fixed: _notify under the private _notify_lock (through the call) and
    a direct observer invocation under the pool lock."""
    findings = _pscan("callback_under_lock_bad.py")
    assert _rules_hit(findings) == ["CALLBACK-UNDER-LOCK"]
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "_notify" in messages
    assert "on_endpoint_state" in messages


def test_callback_under_lock_clean():
    assert _pscan("callback_under_lock_ok.py") == []


def test_program_rules_are_suppressible_with_reason():
    src = (FIXTURES / "lock_inv_bad.py").read_text()
    src = src.replace(
        "with self._audit_lock:\n            with self._write_lock:",
        "with self._audit_lock:\n            # tpulint: disable=LOCK-INV"
        " -- fixture: suppression check\n"
        "            with self._write_lock:",
    )
    path = FIXTURES / "lock_inv_bad.py"
    import tempfile, os  # noqa: E401

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "lock_inv_suppressed.py")
        with open(p, "w") as fh:
            fh.write(src)
        assert scan_paths([p]) == []
    assert path.exists()  # the real fixture is untouched


def test_callgraph_resolution():
    """self-calls, cross-module imports, constructors, and the unique
    arity-compatible method fallback all resolve; ambiguity does not."""
    src_a = (
        "from pkg_b import helper\n"
        "class A:\n"
        "    def run(self):\n"
        "        self.step()\n"
        "        helper()\n"
        "        B()\n"
        "    def step(self):\n"
        "        pass\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        pass\n"
    )
    src_b = (
        "def helper():\n"
        "    pass\n"
        "class C:\n"
        "    def only_here(self, x):\n"
        "        pass\n"
    )
    mod_a = callgraph.summarize_module(ast.parse(src_a), "pkg_a.py")
    mod_b = callgraph.summarize_module(ast.parse(src_b), "pkg_b.py")
    prog = callgraph.build_program([mod_a, mod_b])
    run = mod_a.functions["A.run"]
    _m, fn = prog.resolve(mod_a, run, ("self", "step"))
    assert fn is not None and fn.qualname == "A.step"
    _m, fn = prog.resolve(mod_a, run, ("name", "helper"))
    assert fn is not None and fn.qualname == "helper"
    _m, fn = prog.resolve(mod_a, run, ("name", "B"))
    assert fn is not None and fn.qualname == "B.__init__"
    # unique-method fallback honors arity (only_here takes exactly one)
    _m, fn = prog.resolve(mod_a, run, ("method", "only_here"), 1)
    assert fn is not None and fn.qualname == "C.only_here"
    _m, fn = prog.resolve(mod_a, run, ("method", "only_here"), 3)
    assert fn is None
    _m, fn = prog.resolve(mod_a, run, ("method", "nowhere"), 0)
    assert fn is None


def test_callgraph_lock_summaries():
    """Held sets, *_locked convention, and deferred Thread targets."""
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        t = threading.Thread(target=self._loop)\n"
        "    def work(self):\n"
        "        with self._lock:\n"
        "            self.flush_locked()\n"
        "    def flush_locked(self):\n"
        "        pass\n"
        "    def _loop(self):\n"
        "        pass\n"
    )
    mod = callgraph.summarize_module(ast.parse(src), "s.py")
    work = mod.functions["S.work"]
    assert work.acquisitions[0]["lock"] == "S._lock"
    (call,) = [c for c in work.calls if c["ref"] == ("self", "flush_locked")]
    assert call["held"] == ["S._lock"]
    assert mod.functions["S.flush_locked"].requires_lock
    init = mod.functions["S.__init__"]
    deferred = [c for c in init.calls if c["deferred"]]
    assert deferred and deferred[0]["ref"] == ("self", "_loop")
    assert deferred[0]["held"] == []


def test_callgraph_chained_receivers_keep_their_subtrees():
    """A call through a computed receiver (self._factory().dispatch(),
    self._map[0].append(1)) must not swallow the inner call edge or the
    field access riding in the func subtree."""
    src = (
        "class A:\n"
        "    def run(self):\n"
        "        self._factory().dispatch()\n"
        "    def _factory(self):\n"
        "        return self\n"
        "    def use(self):\n"
        "        self._map[0].append(1)\n"
    )
    mod = callgraph.summarize_module(ast.parse(src), "a.py")
    assert [c["ref"] for c in mod.functions["A.run"].calls] == [
        ("self", "_factory")
    ]
    accesses = mod.functions["A.use"].accesses
    assert [(a["attr"], a["deep"]) for a in accesses] == [("_map", True)]


def test_summary_roundtrip_is_lossless():
    src = (FIXTURES / "lock_inv_bad.py").read_text()
    mod = callgraph.summarize_module(ast.parse(src), "lock_inv_bad.py")
    back = callgraph.ModuleSummary.from_dict(
        json.loads(json.dumps(mod.to_dict()))
    )
    assert back.to_dict() == mod.to_dict()


# -- suppression reasons (BARE-SUPPRESS) -----------------------------------

def test_bare_suppress_hits():
    """A reason-less waiver still suppresses its rule but is itself a
    finding — both targeted and blanket forms."""
    findings = _scan("bare_suppress_bad.py")
    assert _rules_hit(findings) == ["BARE-SUPPRESS"]
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "TIME-WALL" in messages and "all rules" in messages


def test_bare_suppress_cannot_waive_itself():
    src = "import time\nx = 1  # tpulint: disable\n"
    findings = scan_source(src, "x.py")
    assert _rules_hit(findings) == ["BARE-SUPPRESS"]


def test_reasoned_suppressions_are_clean():
    assert _scan("suppressed_ok.py") == []


def test_suppression_reason_may_reference_an_issue_number():
    """`-- #1234` is a reason (an audit trail, even): the tail must not
    stop at the first '#'."""
    src = (
        "import time\n"
        "deadline = time.time() + 5"
        "  # tpulint: disable=TIME-WALL -- #1234: wall clock ok here\n"
    )
    assert scan_source(src, "issue_ref.py") == []


def test_docstring_mention_is_not_a_suppression():
    """Prose inside docstrings/strings that mentions the syntax is
    neither a suppression nor a BARE-SUPPRESS finding (tokenizer-based
    comment detection)."""
    src = (
        '"""Docs: waive with `# tpulint: disable=RULE`."""\n'
        'MSG = "x  # tpulint: disable"\n'
    )
    assert scan_source(src, "docs.py") == []


# -- incremental cache ------------------------------------------------------

def test_cache_roundtrip_and_invalidation(tmp_path):
    cache_file = tmp_path / "cache.json"
    target = tmp_path / "mod.py"
    target.write_text(
        (FIXTURES / "lock_inv_bad.py").read_text()
    )
    c1 = cache_mod.AnalysisCache(str(cache_file))
    cold = scan_paths([str(target)], cache=c1)
    assert _rules_hit(cold) == ["LOCK-INV"]
    assert c1.misses >= 1 and cache_file.exists()

    c2 = cache_mod.AnalysisCache(str(cache_file))
    warm = scan_paths([str(target)], cache=c2)
    assert c2.hits == 1 and c2.misses == 0
    assert [f.to_dict() for f in warm] == [f.to_dict() for f in cold]

    # editing the file invalidates its entry
    time.sleep(0.01)
    target.write_text((FIXTURES / "lock_inv_ok.py").read_text())
    c3 = cache_mod.AnalysisCache(str(cache_file))
    fixed = scan_paths([str(target)], cache=c3)
    assert fixed == []
    assert c3.misses == 1


def test_cache_ignored_for_filtered_scans(tmp_path):
    """A --rules-filtered scan must neither read nor poison the cache."""
    cache_file = tmp_path / "cache.json"
    target = tmp_path / "mod.py"
    target.write_text((FIXTURES / "cv_wait_bad.py").read_text())
    c = cache_mod.AnalysisCache(str(cache_file))
    filtered = scan_paths(
        [str(target)], rules={"NPY-TRUTH": REGISTRY["NPY-TRUTH"]},
        cache=c, program_rules={},
    )
    assert filtered == []
    assert not cache_file.exists()  # nothing cached
    full = scan_paths([str(target)], cache=c)
    assert _rules_hit(full) == ["CV-WAIT-LOOP"]


def test_corrupt_cache_degrades_to_full_scan(tmp_path):
    cache_file = tmp_path / "cache.json"
    cache_file.write_text("{not json")
    c = cache_mod.AnalysisCache(str(cache_file))
    target = tmp_path / "mod.py"
    target.write_text((FIXTURES / "cv_wait_bad.py").read_text())
    findings = scan_paths([str(target)], cache=c)
    assert _rules_hit(findings) == ["CV-WAIT-LOOP"]


def test_cache_entry_stored_against_pre_read_stat(tmp_path):
    """The stat key is captured BEFORE the file is read: a save landing
    mid-analysis must leave the entry looking stale (re-scan next run),
    never fresh (which would serve findings for content nobody
    analyzed)."""
    target = tmp_path / "mod.py"
    target.write_text("x = 1\n")
    c = cache_mod.AnalysisCache(str(tmp_path / "cache.json"))
    key = c.stat_key(str(target))
    time.sleep(0.01)
    target.write_text("y = 2  # saved between stat and put\n")
    c.put(str(target), {"findings": []}, key)
    assert c.get(str(target)) is None  # stale → miss → re-scan


def test_absolute_scan_roots_resolve_cross_module_calls(tmp_path):
    """Module identity must match what `import` statements name however
    the scan root is spelled: an absolute CI path and a relative dev path
    produce the same program (and the same interprocedural findings)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text(
        "import threading\n"
        "from pkg.b import helper\n\n\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._la = threading.Lock()\n\n"
        "    def go(self):\n"
        "        with self._la:\n"
        "            helper()\n"
    )
    (pkg / "b.py").write_text(
        "import time\n\n\n"
        "def helper():\n"
        "    time.sleep(1.0)\n"
    )
    findings = scan_paths([str(pkg)])  # absolute root
    assert _rules_hit(findings) == ["BLOCK-UNDER-LOCK"]
    assert "A.go -> helper" in findings[0].message


# -- LOCKSET-RACE (Eraser-style lockset inference) -------------------------

def test_lockset_race_hits_live_pre_fix_shapes():
    """Each class freezes one live catch this PR fixed: the unguarded
    cross-root counter (metrics_manager.scrape_errors), the lock-free
    memoization dict iterated caller-side (engine._tick_jits), the
    unguarded late-bind rebind (pre-fix set_registry), and the split
    guard (write under lock A, read under lock B) — reached two calls
    deep, proving the interprocedural chain."""
    findings = _pscan("lockset_race_bad.py")
    races = [f for f in findings if f.rule == "LOCKSET-RACE"]
    fields = sorted(
        f.message.split("field ")[1].split(" ")[0] for f in races
    )
    assert fields == [
        "Publisher.registry", "ScrapeLoop.scrape_errors",
        "SplitGuard._inflight", "TickEngine._jits",
    ]
    # the unguarded-rebind shape is ALSO the lexical SHARED-MUT catch —
    # overlap expected there, and nowhere else
    assert _rules_hit(findings) == ["LOCKSET-RACE", "SHARED-MUT"]
    split = next(f for f in races if "SplitGuard" in f.message)
    # both witness sites ride in the finding: holding sets + root chains
    assert "_stats_lock" in split.message and "_lock" in split.message
    assert "<main>" in split.message and "_loop" in split.message
    assert "SplitGuard.note -> " in split.message  # the chain, not just the site


def test_lockset_race_split_guard_is_invisible_to_shared_mut():
    """The gap the rule closes: every SplitGuard access is lexically
    'under a lock', so the per-file rule cannot see the disjoint guard
    sets."""
    lexical = scan_source(
        (FIXTURES / "lockset_race_bad.py").read_text(),
        str(FIXTURES / "lockset_race_bad.py"),
    )
    assert not any(
        "SplitGuard" in f.message or "_inflight" in f.message
        for f in lexical
    )


def test_lockset_race_clean_twins():
    """Post-fix shapes and every documented exemption (consistent
    guard, safe publication, init-only, single-root, *_locked
    convention) scan clean through every rule family."""
    assert _pscan("lockset_race_ok.py") == []


def test_lockset_race_spawner_writes_are_virgin_phase(tmp_path):
    """Writes in the method that REGISTERS the thread (`start()` spawns
    last — the repo-wide idiom) share __init__'s exemption; the same
    write moved into a post-start method is a finding."""
    template = (
        "import threading\n\n\n"
        "class Srv:\n"
        "    def __init__(self):\n"
        "        self.limit = 0\n\n"
        "    def {method}\n"
        "        self.limit = 8\n{extra}"
        "    def _loop(self):\n"
        "        while True:\n"
        "            try:\n"
        "                if self.limit:\n"
        "                    return\n"
        "            except Exception:\n"
        "                return\n"
    )
    spawner = template.format(
        method="start(self):",
        extra=(
            "        t = threading.Thread(target=self._loop)\n"
            "        t.start()\n\n"
    ))
    late = template.format(
        method="resize(self):",
        extra=(
            "\n    def start(self):\n"
            "        t = threading.Thread(target=self._loop)\n"
            "        t.start()\n\n"
    ))
    from client_tpu.analysis import PROGRAM_REGISTRY as PR

    lockset_only = {"LOCKSET-RACE": PR["LOCKSET-RACE"]}
    p = tmp_path / "srv.py"
    p.write_text(spawner)
    assert scan_paths([str(p)], rules={}, program_rules=lockset_only) == []
    p.write_text(late)
    findings = scan_paths(
        [str(p)], rules={}, program_rules=lockset_only
    )
    assert _rules_hit(findings) == ["LOCKSET-RACE"]
    assert "Srv.limit" in findings[0].message


def test_lockset_race_self_synced_delegate_exemption(tmp_path):
    """Delegating to a lock-OWNING class (the fleet seq_store shape) is
    self-synchronized and silent; the identical delegation to a
    lock-less class is a race."""
    template = (
        "import threading\n\n\n"
        "class Store:\n"
        "    def __init__(self):\n{store_init}"
        "        self._entries = {{}}\n\n"
        "    def get(self, k):\n"
        "        return self._entries.get(k)\n\n"
        "    def pop(self, k):\n"
        "        self._entries.pop(k, None)\n\n\n"
        "class Tier:\n"
        "    def __init__(self):\n"
        "        self.store = Store()\n"
        "        t = threading.Thread(target=self._loop)\n"
        "        t.start()\n\n"
        "    def forget(self, k):\n"
        "        self.store.pop(k)\n\n"
        "    def _loop(self):\n"
        "        while True:\n"
        "            try:\n"
        "                self.store.get(0)\n"
        "            except Exception:\n"
        "                return\n"
    )
    from client_tpu.analysis import PROGRAM_REGISTRY as PR

    lockset_only = {"LOCKSET-RACE": PR["LOCKSET-RACE"]}
    p = tmp_path / "tier.py"
    p.write_text(template.format(
        store_init="        self._lock = threading.Lock()\n"
    ))
    assert scan_paths([str(p)], rules={}, program_rules=lockset_only) == []
    p.write_text(template.format(store_init=""))
    findings = scan_paths(
        [str(p)], rules={}, program_rules=lockset_only
    )
    assert _rules_hit(findings) == ["LOCKSET-RACE"]
    assert "Tier.store" in findings[0].message


def test_lockset_race_suppressible_with_reason(tmp_path):
    src = (FIXTURES / "lockset_race_bad.py").read_text()
    src = src.replace(
        "self._jits[n] = object()  # racy: insert outside _cv",
        "self._jits[n] = object()  # tpulint: disable=LOCKSET-RACE"
        " -- fixture: suppression check",
    )
    p = tmp_path / "suppressed.py"
    p.write_text(src)
    findings = scan_paths([str(p)])
    assert not any("TickEngine" in f.message for f in findings)


# -- resource-lifecycle analysis (ownership tracking + leak rules) ----------

def test_resource_leak_hits():
    """The four leak shapes: a lease released only on the ok path, an
    early return between alloc and release, a socket never closed, and —
    the interprocedural case the lexical rules cannot see — a KV
    reservation acquired through a wrapper (`self._fresh` returns
    `alloc`'s result) and then dropped."""
    findings = _pscan("resource_leak_bad.py")
    assert _rules_hit(findings) == ["RESOURCE-LEAK"]
    assert sorted(f.line for f in findings) == [16, 26, 36, 46]
    messages = {f.line: f.message for f in findings}
    assert "only on some paths" in messages[16]
    assert "return path" in messages[26]
    assert "never releases or transfers" in messages[36]
    # the wrapper acquisition is attributed through the call chain
    assert "self._fresh()" in messages[46]
    assert "KV block reservation" in messages[46]


def test_resource_leak_clean():
    """Every safe custody shape — try/finally, release on all try arms,
    `with`, ownership transfer to a storing callee, None-guard, daemon
    thread, started-then-joined thread — scans clean through every rule
    family."""
    assert _pscan("resource_leak_ok.py") == []


def test_double_release_hits():
    """Sequential double release and release-in-body-plus-finally (the
    finally re-runs on the no-raise path) both pair on one path."""
    findings = _pscan("double_release_bad.py")
    assert _rules_hit(findings) == ["DOUBLE-RELEASE"]
    assert sorted(f.line for f in findings) == [18, 27]
    for f in findings:
        assert "twice on one path" in f.message


def test_double_release_clean():
    """Either-or releases (if/else arms, except vs the no-raise path)
    are one release; the path algebra must never pair them."""
    assert _pscan("double_release_ok.py") == []


def test_use_after_release_hits():
    """A freed block index spliced into a table and a read on a closed
    file — both uses on the same sequential path as the release."""
    findings = _pscan("use_after_release_bad.py")
    assert _rules_hit(findings) == ["USE-AFTER-RELEASE"]
    assert sorted(f.line for f in findings) == [16, 23]
    for f in findings:
        assert "after releasing it" in f.message


def test_use_after_release_clean():
    """Release-in-one-arm/use-in-the-other and use-inside-try-with-
    finally-close are the normal hand-off shapes."""
    assert _pscan("use_after_release_ok.py") == []


def test_resource_leak_exception_edge(tmp_path):
    """A release that lives only in the except handler covers only the
    exception edge — the no-raise path walks out with the reservation
    still held; routing the release through a finally covers both."""
    leaky = tmp_path / "leaky.py"
    leaky.write_text(
        "def fetch(pool, n, sink):\n"
        "    blocks = pool.alloc(n)\n"
        "    if blocks is None:\n"
        "        return None\n"
        "    try:\n"
        "        sink.push(n)\n"
        "    except ValueError:\n"
        "        pool.release(blocks)\n"
        "        raise\n"
        "    return n\n"
    )
    findings = scan_paths([str(leaky)])
    assert _rules_hit(findings) == ["RESOURCE-LEAK"]
    assert "only on some paths" in findings[0].message
    fixed = tmp_path / "fixed.py"
    fixed.write_text(
        "def fetch(pool, n, sink):\n"
        "    blocks = pool.alloc(n)\n"
        "    if blocks is None:\n"
        "        return None\n"
        "    try:\n"
        "        sink.push(n)\n"
        "    finally:\n"
        "        pool.release(blocks)\n"
        "    return n\n"
    )
    assert scan_paths([str(fixed)]) == []


def test_resource_transfer_to_storing_callee_is_ownership(tmp_path):
    """Passing the handle to a callee that stores it on self is a
    custody transfer — the caller is off the hook; passing it to a
    callee the program cannot resolve gets the same benefit of the
    doubt (FN over FP)."""
    mod = tmp_path / "transfer.py"
    mod.write_text(
        "from somewhere import ship_out\n\n\n"
        "class Table:\n"
        "    def adopt(self, blocks):\n"
        "        self._rows = blocks\n\n"
        "    def admit(self, pool, n):\n"
        "        blocks = pool.alloc(n)\n"
        "        if blocks is None:\n"
        "            return\n"
        "        self.adopt(blocks)\n\n\n"
        "def export(pool, n):\n"
        "    blocks = pool.alloc(n)\n"
        "    if blocks is None:\n"
        "        return\n"
        "    ship_out(blocks)\n"
    )
    assert scan_paths([str(mod)]) == []


def test_wrapper_acquired_span_leak_is_interprocedural(tmp_path):
    """A span acquired through a helper (`return tracer.sample(...)`)
    and never completed: the lexical SPAN-LEAK rule cannot see through
    the call, the ownership engine can."""
    mod = tmp_path / "spans.py"
    mod.write_text(
        "def span_for(tracer, name):\n"
        "    return tracer.sample(name)\n\n\n"
        "def handle(tracer, payload):\n"
        "    span = span_for(tracer, 'handle')\n"
        "    return len(payload)\n"
    )
    findings = scan_paths([str(mod)])
    assert "RESOURCE-LEAK" in _rules_hit(findings)
    assert any("span_for()" in f.message for f in findings)
    fixed = tmp_path / "spans_ok.py"
    fixed.write_text(
        "def span_for(tracer, name):\n"
        "    return tracer.sample(name)\n\n\n"
        "def handle(tracer, payload):\n"
        "    span = span_for(tracer, 'handle')\n"
        "    try:\n"
        "        return len(payload)\n"
        "    finally:\n"
        "        span.complete(ok=True)\n"
    )
    assert scan_paths([str(fixed)]) == []


# -- STALE-SUPPRESS (waiver audit) ------------------------------------------

def test_stale_suppress_hits():
    """A waiver outliving its hazard is a finding: the fixed-long-ago
    TIME-WALL waiver, the half-stale multi-rule list (only the dead id
    reported), and a blanket waiver over nothing."""
    findings = _pscan("stale_suppress_bad.py")
    assert _rules_hit(findings) == ["STALE-SUPPRESS"]
    assert len(findings) == 3
    messages = " ".join(f.message for f in findings)
    assert "TIME-WALL" in messages
    assert "NPY-TRUTH" in messages
    assert "any rule" in messages
    # the comment line rides as the snippet: distinct stale waivers in
    # one file stay distinct under the baseline's (path, rule, snippet)
    # key
    assert all(f.snippet for f in findings)
    assert len({f.key() for f in findings}) == 3


def test_stale_suppress_clean_when_waivers_fire():
    assert _pscan("stale_suppress_ok.py") == []


def test_stale_suppress_needs_full_scan():
    """scan_source (one file, per-file rules only) and --rules-filtered
    runs cannot tell 'unused' from 'unchecked': STALE-SUPPRESS only
    reports on full scans."""
    src = (FIXTURES / "stale_suppress_bad.py").read_text()
    assert "STALE-SUPPRESS" not in _rules_hit(
        scan_source(src, "stale_suppress_bad.py")
    )
    filtered = scan_paths(
        [str(FIXTURES / "stale_suppress_bad.py")],
        rules={"TIME-WALL": REGISTRY["TIME-WALL"]}, program_rules={},
    )
    assert "STALE-SUPPRESS" not in _rules_hit(filtered)


def test_stale_suppress_cannot_waive_itself(tmp_path):
    src = (
        "import time\n\n\n"
        "def f():\n"
        "    # tpulint: disable=STALE-SUPPRESS -- meta-waiver\n"
        "    x = 1  # tpulint: disable=TIME-WALL -- long gone\n"
        "    return x\n"
    )
    p = tmp_path / "meta.py"
    p.write_text(src)
    findings = scan_paths([str(p)])
    rules = [f.rule for f in findings]
    # the TIME-WALL waiver is stale AND the meta-waiver (which fired on
    # nothing it may waive) is itself stale — neither can hide
    assert rules.count("STALE-SUPPRESS") == 2


def test_stale_suppress_quoting_prose_is_not_a_directive():
    """A comment QUOTING the syntax mid-text (like the analyzer's own
    docs) is neither a suppression nor stale — the directive must start
    the comment."""
    src = (
        "# usage: waive with `# tpulint: disable=NPY-TRUTH -- why`\n"
        "x = 1\n"
    )
    assert scan_source(src, "docs.py") == []


# -- whole-program pass cache (fileset digest) ------------------------------

def test_program_pass_cached_under_fileset_digest(tmp_path):
    """Touch nothing -> per-file AND program results come from cache;
    edit one file -> only that file re-analyzes, the program pass
    reruns (and its verdict tracks the edit)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text(
        "import threading\n"
        "from pkg.b import helper\n\n\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._la = threading.Lock()\n\n"
        "    def go(self):\n"
        "        with self._la:\n"
        "            helper()\n"
    )
    (pkg / "b.py").write_text(
        "import time\n\n\n"
        "def helper():\n"
        "    time.sleep(1.0)\n"
    )
    cache_file = tmp_path / "cache.json"

    c1 = cache_mod.AnalysisCache(str(cache_file))
    cold = scan_paths([str(pkg)], cache=c1)
    assert _rules_hit(cold) == ["BLOCK-UNDER-LOCK"]
    assert c1.program_misses == 1

    c2 = cache_mod.AnalysisCache(str(cache_file))
    warm = scan_paths([str(pkg)], cache=c2)
    assert [f.to_dict() for f in warm] == [f.to_dict() for f in cold]
    assert c2.hits == 3 and c2.misses == 0
    assert c2.program_hits == 1 and c2.program_misses == 0

    # edit ONE file: only it re-analyzes; the program pass reruns and
    # its verdict tracks the edit (the blocking callee went bounded)
    time.sleep(0.01)
    (pkg / "b.py").write_text(
        "import time\n\n\n"
        "def helper():\n"
        "    pass\n"
    )
    c3 = cache_mod.AnalysisCache(str(cache_file))
    fixed = scan_paths([str(pkg)], cache=c3)
    assert fixed == []
    assert c3.misses == 1 and c3.hits == 2
    assert c3.program_misses == 1


def test_program_cache_ignored_for_filtered_scans(tmp_path):
    """A --rules-filtered scan must not consume (or poison) the cached
    program verdict."""
    pkg = tmp_path / "mod.py"
    pkg.write_text((FIXTURES / "lock_inv_bad.py").read_text())
    cache_file = tmp_path / "cache.json"
    c1 = cache_mod.AnalysisCache(str(cache_file))
    full = scan_paths([str(pkg)], cache=c1)
    assert _rules_hit(full) == ["LOCK-INV"]
    c2 = cache_mod.AnalysisCache(str(cache_file))
    filtered = scan_paths(
        [str(pkg)], cache=c2,
        program_rules={"LOCK-INV": PROGRAM_REGISTRY["LOCK-INV"]},
    )
    assert _rules_hit(filtered) == ["LOCK-INV"]
    assert c2.program_hits == 0  # filtered scans recompute


# -- dynamic lock-order witness ---------------------------------------------

def test_witness_detects_abba_cycle():
    w = LockWitness()
    a = w.wrap_lock(threading.Lock(), "A")
    b = w.wrap_lock(threading.Lock(), "B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=ba)
    t2.start()
    t2.join()
    assert w.cycles()
    try:
        w.assert_acyclic()
    except LockOrderViolation as e:
        assert "A" in str(e) and "B" in str(e)
    else:
        raise AssertionError("cycle not reported")


def test_witness_consistent_order_is_acyclic():
    w = LockWitness()
    a = w.wrap_lock(threading.Lock(), "A")
    b = w.wrap_lock(threading.Lock(), "B")
    for _ in range(3):
        with a:
            with b:
                pass
    edges = w.assert_acyclic()
    assert edges == 1
    assert w.edges()[("A", "B")] == 3


def test_witness_condition_wait_releases_held_entry():
    """cv.wait() drops the cv from the held stack for its duration: a
    peer acquiring other locks while we wait must not create edges from
    the cv we are not actually holding."""
    w = LockWitness()
    cv = w.wrap_condition(threading.Condition(), "CV")
    other = w.wrap_lock(threading.Lock(), "L")
    ready = threading.Event()

    def waiter():
        with cv:
            ready.set()
            # tpulint: disable=CV-WAIT-LOOP -- witness test: one waiter,
            cv.wait(timeout=2)

    t = threading.Thread(target=waiter)
    t.start()
    ready.wait(2)
    with other:
        pass  # runs while the waiter sits in wait(): no held overlap
    with cv:
        cv.notify_all()
    t.join(2)
    w.assert_acyclic()
    assert ("CV", "L") not in w.edges()


def test_witness_installed_scopes_to_client_tpu():
    """The threading patch wraps locks built under client_tpu/ and leaves
    stdlib-internal allocations (queue.Queue, Condition's private RLock)
    raw — the _is_owned compatibility hazard."""
    import queue

    from client_tpu.serve.frontdoor import Coalescer

    w = LockWitness()
    with w.installed():
        co = Coalescer()
        q = queue.Queue()
        local = threading.Lock()  # test file: not under client_tpu/
    assert type(co._lock).__name__ == "WitnessLock"
    assert "frontdoor" in co._lock._name
    assert type(q.mutex).__name__ != "WitnessLock"
    assert type(local).__name__ != "WitnessLock"
    # and a condition built by repo code keeps working end to end
    with w.installed():
        from client_tpu.serve._completion import CompletionObserver

        obs = CompletionObserver()
        ran = []
        obs.watch({}, lambda: ran.append(1))  # host result: inline
        obs.close()
    assert ran == [1]
    w.assert_acyclic()


def test_witness_prefix_matches_packages_not_path_substrings(tmp_path):
    """A checkout directory that happens to be NAMED client_tpu (the
    default `git clone` name) must not pull every file under it into
    witness scope — only a real package root (carrying __init__.py)
    counts."""
    def build_lock_in(directory):
        mod = directory / "maker.py"
        mod.write_text("import threading\nlock = threading.Lock()\n")
        ns = {}
        code = compile(mod.read_text(), str(mod), "exec")
        w = LockWitness()
        with w.installed():
            exec(code, ns)
        return ns["lock"]

    checkout = tmp_path / "client_tpu"  # no __init__.py: just a dir
    checkout.mkdir()
    assert type(build_lock_in(checkout)).__name__ != "WitnessLock"

    pkg = tmp_path / "real" / "client_tpu"  # a package root
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    assert type(build_lock_in(pkg)).__name__ == "WitnessLock"


# -- dynamic race witness ---------------------------------------------------

def _racy_pair(witness):
    """A guarded/unguarded class pair whose lock is witness-wrapped (the
    fixture files live outside client_tpu/, so installed()'s automatic
    construction-site wrapping does not apply here)."""
    class Shared:
        def __init__(self):
            self._lock = witness.wrap_lock(threading.Lock(), "Shared._lock")
            self.count = 0

        def bump_locked_path(self):
            with self._lock:
                self.count = self.count + 1

        def bump_unguarded(self):
            self.count = self.count + 1

    return Shared


def _hammer(fn, n=50, threads=3, collect=None):
    from client_tpu.analysis.witness import RaceViolation

    def run():
        try:
            for _ in range(n):
                fn()
        except RaceViolation as exc:
            if collect is not None:
                collect.append(exc)

    ts = [threading.Thread(target=run) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def test_race_witness_fires_on_seeded_unguarded_write(tmp_path):
    """The acceptance bullet: a deliberately seeded unguarded write
    raises with BOTH stack traces and dumps to the flight recorder."""
    from client_tpu.analysis.witness import RaceViolation, RaceWitness
    from client_tpu.serve.flight import FlightRecorder

    flight = FlightRecorder(dump_dir=str(tmp_path), name="race-test")
    w = RaceWitness(flight=flight)
    Shared = _racy_pair(w)
    w.watch_class(Shared, guards=("_lock",))
    caught = []
    with w.installed():
        obj = Shared()
        _hammer(obj.bump_unguarded, collect=caught)
    assert caught, "seeded unguarded write did not raise"
    report = str(caught[0])
    assert "Shared.count" in report
    assert "this access:" in report and "prior conflicting access:" in report
    assert report.count("thread ") >= 2  # both stacks, both threads
    # ...and the evidence landed in the flight recorder ring + on disk
    kinds = [r["kind"] for r in flight.snapshot()]
    assert "race_witness_violation" in kinds
    assert flight.dumps and "race-Shared-count" in flight.dumps[0]
    try:
        w.assert_race_free()
    except RaceViolation:
        pass
    else:
        raise AssertionError("assert_race_free stayed green")


def test_race_witness_silent_on_guarded_writes():
    from client_tpu.analysis.witness import RaceWitness

    w = RaceWitness()
    Shared = _racy_pair(w)
    w.watch_class(Shared, guards=("_lock",))
    with w.installed():
        obj = Shared()
        _hammer(obj.bump_locked_path)
    assert w.assert_race_free() > 0  # it watched, and stayed green


def test_race_witness_first_thread_exclusive_exempt():
    """A single thread may write unguarded all day — Eraser's exclusive
    phase; __init__ writes ride the same exemption."""
    from client_tpu.analysis.witness import RaceWitness

    w = RaceWitness()
    Shared = _racy_pair(w)
    w.watch_class(Shared, guards=("_lock",))
    with w.installed():
        obj = Shared()
        for _ in range(100):
            obj.bump_unguarded()
    assert w.assert_race_free() > 0


def test_race_witness_tolerates_published_reads():
    """Guarded rebinds + lock-free reference reads (the post-fix
    set_registry shape): the witness checks the WRITE-side protocol,
    mirroring the static pass's safe-publication exemption."""
    from client_tpu.analysis.witness import RaceWitness

    w = RaceWitness()

    class Published:
        def __init__(self):
            self._lock = w.wrap_lock(threading.Lock(), "P._lock")
            self.ref = None

        def publish(self, value):
            with self._lock:
                self.ref = value

    w.watch_class(Published, guards=("_lock",))
    with w.installed():
        obj = Published()
        t = threading.Thread(
            target=lambda: [obj.publish(i) for i in range(200)]
        )
        t.start()
        for _ in range(200):
            _ = obj.ref  # lock-free reference load: GIL-atomic
        t.join()
    assert w.assert_race_free() > 0


def test_race_witness_decorator_and_restore():
    """@witness_shared costs nothing unarmed; installed() instruments
    the decorated class and restores it exactly on exit."""
    from client_tpu.analysis.witness import RaceWitness, witness_shared

    @witness_shared("_lock")
    class Decorated:
        def __init__(self):
            self._lock = threading.Lock()
            self.value = 0

    before_set = Decorated.__setattr__
    before_get = Decorated.__getattribute__
    w = RaceWitness()
    with w.installed():
        assert Decorated.__setattr__ is not before_set
        obj = Decorated()
        obj.value = 1
        _ = obj.value
    assert Decorated.__setattr__ is before_set
    assert Decorated.__getattribute__ is before_get
    assert w.field_accesses >= 2  # the armed window recorded traffic


def test_race_witness_is_also_the_lock_order_witness():
    """RaceWitness keeps full LockWitness duty: the ABBA cycle is still
    caught while race instrumentation is armed."""
    from client_tpu.analysis.witness import RaceWitness

    w = RaceWitness()
    a = w.wrap_lock(threading.Lock(), "A")
    b = w.wrap_lock(threading.Lock(), "B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    assert w.cycles()
    assert w.assert_race_free() == 0  # no witnessed fields, no races


def test_chaos_race_invariant_helper():
    """assert_race_witness_clean: green on None/plain LockWitness, red
    once a RaceWitness recorded a violation."""
    from client_tpu.analysis.witness import (
        LockWitness,
        RaceViolation,
        RaceWitness,
    )
    from client_tpu.testing.chaos import assert_race_witness_clean

    assert assert_race_witness_clean(None) == 0
    assert assert_race_witness_clean(LockWitness()) == 0
    w = RaceWitness()
    Shared = _racy_pair(w)
    w.watch_class(Shared, guards=("_lock",))
    caught = []
    with w.installed():
        obj = Shared()
        _hammer(obj.bump_unguarded, collect=caught)
    assert caught
    try:
        assert_race_witness_clean(w)
    except RaceViolation:
        pass
    else:
        raise AssertionError("race violation not surfaced by the invariant")


# -- CLI: format/explain/cache ----------------------------------------------

def test_cli_format_json_and_alias():
    for flags in (("--format", "json"), ("--json",)):
        proc = _cli(
            "tests/analysis_fixtures/cv_wait_bad.py", *flags,
            "--no-baseline", "--no-cache",
        )
        assert proc.returncode == 1
        payload = json.loads(proc.stdout)
        assert payload["count"] == 1
        assert payload["findings"][0]["rule"] == "CV-WAIT-LOOP"


def test_cli_explain():
    proc = _cli("--explain", "LOCK-INV")
    assert proc.returncode == 0
    assert "lock-order" in proc.stdout.lower()
    proc = _cli("--explain", "BLOCK-UNDER-LOCK")
    assert proc.returncode == 0
    assert "prefill" in proc.stdout.lower()
    proc = _cli("--explain", "NOT-A-RULE")
    assert proc.returncode == 2


def test_cli_fails_on_each_seeded_bad_fixture():
    """The acceptance bullet: the gate exits non-zero on every seeded bad
    fixture for the new rule family."""
    for name, rule in (
        ("lock_inv_bad.py", "LOCK-INV"),
        ("block_under_lock_bad.py", "BLOCK-UNDER-LOCK"),
        ("callback_under_lock_bad.py", "CALLBACK-UNDER-LOCK"),
        ("bare_suppress_bad.py", "BARE-SUPPRESS"),
        ("refcount_pair_bad.py", "REFCOUNT-PAIR"),
        ("bg_thread_crash_bad.py", "BG-THREAD-CRASH"),
        ("span_leak_bad.py", "SPAN-LEAK"),
    ):
        proc = _cli(
            f"tests/analysis_fixtures/{name}", "--no-baseline", "--no-cache"
        )
        assert proc.returncode == 1, (name, proc.stdout, proc.stderr)
        assert rule in proc.stdout


def test_cli_program_rule_selection():
    """--rules works across both families."""
    proc = _cli(
        "tests/analysis_fixtures/lock_inv_bad.py", "--rules", "LOCK-INV",
        "--no-baseline", "--no-cache",
    )
    assert proc.returncode == 1
    proc = _cli(
        "tests/analysis_fixtures/lock_inv_bad.py", "--rules", "NPY-TRUTH",
        "--no-baseline", "--no-cache",
    )
    assert proc.returncode == 0


def test_cli_sarif_output():
    """--format sarif: SARIF 2.1.0 with the finding as an error result,
    the rule catalog in the driver, and 1-based columns."""
    proc = _cli(
        "tests/analysis_fixtures/cv_wait_bad.py", "--format", "sarif",
        "--no-baseline", "--no-cache",
    )
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["version"] == "2.1.0"
    run = payload["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "CV-WAIT-LOOP" in rule_ids and "LOCKSET-RACE" in rule_ids
    (result,) = run["results"]
    assert result["ruleId"] == "CV-WAIT-LOOP"
    assert result["level"] == "error"
    location = result["locations"][0]["physicalLocation"]
    assert location["artifactLocation"]["uri"].endswith("cv_wait_bad.py")
    assert location["region"]["startLine"] >= 1
    assert location["region"]["startColumn"] >= 1


def test_cli_sarif_marks_grandfathered_baseline_state(tmp_path):
    """Baselined findings ride along as level=note with
    baselineState=unchanged so annotators can fold the ratchet debt."""
    # the baseline keys on the path as scanned: generate it through the
    # CLI so the relative spelling matches the gated run below
    proc = _cli(
        "tests/analysis_fixtures/cv_wait_bad.py", "--json",
        "--no-baseline", "--no-cache",
    )
    payload = json.loads(proc.stdout)
    from client_tpu.analysis import Finding

    findings = [Finding(**f) for f in payload["findings"]]
    baseline = tmp_path / "baseline.json"
    baseline_mod.save(str(baseline), findings)
    proc = _cli(
        "tests/analysis_fixtures/cv_wait_bad.py", "--format", "sarif",
        "--baseline", str(baseline), "--no-cache",
    )
    assert proc.returncode == 0  # grandfathered: the gate stays green
    payload = json.loads(proc.stdout)
    (result,) = payload["runs"][0]["results"]
    assert result["level"] == "note"
    assert result["baselineState"] == "unchanged"


def test_cli_changed_only(tmp_path):
    """--changed-only: per-file findings narrow to files changed vs the
    merge base (uncommitted + untracked); committed-clean trees pass
    even when an unchanged file still carries a finding."""
    import os as _os

    repo = tmp_path / "repo"
    repo.mkdir()
    env = dict(
        _os.environ,
        PYTHONPATH=str(ROOT),
        GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
        GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t",
    )

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=str(repo), check=True, env=env,
            capture_output=True, timeout=60,
        )

    def lint(*args):
        return subprocess.run(
            [sys.executable, "-m", "client_tpu.analysis", "pkg",
             "--no-baseline", "--no-cache", *args],
            cwd=str(repo), env=env, capture_output=True, text=True,
            timeout=120,
        )

    pkg = repo / "pkg"
    pkg.mkdir()
    (pkg / "clean.py").write_text("x = 1\n")
    bad = (FIXTURES / "cv_wait_bad.py").read_text()
    git("init", "-q", "-b", "main")
    git("add", ".")
    git("commit", "-qm", "clean seed")

    # an UNTRACKED bad file is in the changed set: the gate fires
    (pkg / "fresh_bad.py").write_text(bad)
    proc = lint("--changed-only")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "CV-WAIT-LOOP" in proc.stdout

    # committed: vs the merge base nothing changed — the pre-commit
    # path goes green even though a full scan still finds it
    git("add", ".")
    git("commit", "-qm", "carries a finding")
    proc = lint("--changed-only")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = lint()
    assert proc.returncode == 1


# -- dynamic resource witness ------------------------------------------------

def _kv_pool():
    from client_tpu.serve.lm.kv import KvBlockPool
    from client_tpu.serve.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=96, dtype="float32",
    )
    return KvBlockPool(cfg, n_blocks=8, block_size=4)


def test_resource_witness_fires_on_leaked_reservation(tmp_path):
    """A KV reservation still live at the checkpoint raises
    ResourceLeakError carrying the acquisition stack, and dumps the
    live-handle table to the attached flight recorder."""
    import pytest

    from client_tpu.analysis.witness import (
        ResourceLeakError,
        ResourceWitness,
    )
    from client_tpu.serve.flight import FlightRecorder

    flight = FlightRecorder(dump_dir=str(tmp_path), name="leak-test")
    witness = ResourceWitness(flight=flight)
    with witness.installed():
        pool = _kv_pool()
        blocks = pool.alloc(2)
        assert blocks is not None  # deliberately never released
        with pytest.raises(ResourceLeakError) as excinfo:
            witness.assert_clean()
        pool.release(blocks)  # drain: outer session audits stay clean
    msg = str(excinfo.value)
    assert "kv-blocks" in msg and "acquired at" in msg
    # the failed checkpoint shipped its own postmortem
    assert flight.dumps
    kinds = [r["kind"] for r in flight.snapshot()]
    assert "resource_witness_leak" in kinds


def test_resource_witness_silent_after_full_release():
    """alloc + retain = two references per block; two releases drain the
    table and the checkpoint passes, returning the acquisition count (so
    callers can assert the witness actually saw traffic)."""
    from client_tpu.analysis.witness import ResourceWitness

    witness = ResourceWitness()
    with witness.installed():
        pool = _kv_pool()
        blocks = pool.alloc(2)
        pool.retain(blocks)
        pool.release(blocks)
        pool.release(blocks)
        assert witness.assert_clean() == 4  # 2 alloc + 2 retain refs


def test_resource_witness_lease_round_trip():
    """An endpoint lease registers on lease() and retires on any of the
    three release verbs; a second (idempotent) release stays lenient."""
    from client_tpu.analysis.witness import ResourceWitness
    from client_tpu.balance.pool import EndpointPool

    witness = ResourceWitness()
    with witness.installed():
        pool = EndpointPool(["a:1", "b:2"])
        lease = pool.lease()
        assert witness.live()
        lease.success()
        assert witness.assert_clean() == 1
        lease.release()  # idempotent re-release: ignored, still clean
        assert witness.assert_clean() == 1


def test_resource_witness_restores_and_ignores_prior_handles():
    """Handles acquired before arming are invisible (their release is a
    no-op in the table), and after installed() exits the patched
    methods are restored — post-restore traffic never registers."""
    from client_tpu.analysis.witness import ResourceWitness

    pool = _kv_pool()
    pre = pool.alloc(1)  # acquired before the witness armed
    witness = ResourceWitness()
    with witness.installed():
        pool.release(pre)  # pre-arming handle: lenient no-op
        assert witness.assert_clean() == 0
    post = pool.alloc(2)  # after restore: invisible
    try:
        assert witness.assert_clean() == 0
    finally:
        pool.release(post)
