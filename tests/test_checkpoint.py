"""Training checkpoint/resume (client_tpu.train): interrupted training
must continue bit-for-bit from a restore, including onto a sharded mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from client_tpu.parallel import make_mesh, named_shardings, param_specs
from client_tpu.serve.models import transformer as tfm
from client_tpu.train import CheckpointManager

CFG = tfm.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq=32, dtype="float32",
)


def _tokens(key, n=4):
    return jax.random.randint(key, (n, 17), 0, CFG.vocab_size)


def test_save_restore_resume_matches_uninterrupted(tmp_path):
    opt, step = tfm.make_train_step(CFG, learning_rate=1e-2)
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    state = opt.init(params)
    toks = _tokens(jax.random.PRNGKey(1))

    # uninterrupted: 6 steps
    p_ref = jax.tree.map(jnp.copy, params)
    s_ref = jax.tree.map(jnp.copy, state)
    for _ in range(6):
        p_ref, s_ref, loss_ref = step(p_ref, s_ref, toks)

    # interrupted: 3 steps, checkpoint, fresh restore, 3 more
    p = jax.tree.map(jnp.copy, params)
    s = jax.tree.map(jnp.copy, state)
    for _ in range(3):
        p, s, _ = step(p, s, toks)
    with CheckpointManager(tmp_path / "ckpt") as mgr:
        mgr.save(3, params=p, opt_state=s)
        assert mgr.latest_step() == 3
        template = {"params": params, "opt_state": state}
        restored = mgr.restore(template)
    p2, s2 = restored["params"], restored["opt_state"]
    for _ in range(3):
        p2, s2, loss2 = step(p2, s2, toks)
    assert float(loss2) == pytest.approx(float(loss_ref), rel=1e-6)
    np.testing.assert_array_equal(
        np.asarray(p2["lm_head"]), np.asarray(p_ref["lm_head"])
    )


def test_restore_onto_sharded_mesh(tmp_path):
    """A mesh-sharded template restores each leaf onto its mesh sharding."""
    mesh = make_mesh(dp=2, tp=2, sp=2)
    params = tfm.init_params(jax.random.PRNGKey(2), CFG)
    with CheckpointManager(tmp_path / "ckpt") as mgr:
        mgr.save(0, params=params)
        sharded_template = jax.device_put(
            params, named_shardings(mesh, param_specs(CFG))
        )
        restored = mgr.restore({"params": sharded_template}, step=0)
    leaf = restored["params"]["layers"][0]["attn"]["wq"]
    assert leaf.sharding == sharded_template["layers"][0]["attn"]["wq"].sharding
    np.testing.assert_array_equal(
        np.asarray(leaf), np.asarray(params["layers"][0]["attn"]["wq"])
    )
    # and a sharded train step runs straight off the restored state
    opt, step = tfm.make_train_step(CFG, mesh=mesh, attn_impl="ring")
    state = opt.init(restored["params"])
    toks = jax.device_put(
        _tokens(jax.random.PRNGKey(3)),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("dp", None)),
    )
    _, _, loss = step(restored["params"], state, toks)
    assert np.isfinite(float(loss))


def test_retention_and_missing(tmp_path):
    params = {"w": jnp.ones((4,))}
    with CheckpointManager(tmp_path / "ckpt", max_to_keep=2) as mgr:
        with pytest.raises(FileNotFoundError):
            mgr.restore(params)
        for i in range(4):
            mgr.save(i, **params)
        kept = mgr.all_steps()
        assert kept == [2, 3]
