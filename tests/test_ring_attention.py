"""Ring attention vs plain attention on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from client_tpu.parallel import make_mesh
from client_tpu.parallel.ring_attention import (
    plain_attention,
    ring_attention_sharded,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=2, tp=2, sp=2)


def _rand_qkv(key, b=2, t=16, h=4, d=8, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, t, h, d), dtype) for k in ks)


def test_ring_matches_plain_causal(mesh):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    expected = plain_attention(q, k, v, causal=True)
    got = ring_attention_sharded(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_ring_matches_plain_noncausal(mesh):
    q, k, v = _rand_qkv(jax.random.PRNGKey(1))
    expected = plain_attention(q, k, v, causal=False)
    got = ring_attention_sharded(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_causality(mesh):
    """Perturbing future tokens must not change earlier outputs."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(2))
    base = np.asarray(ring_attention_sharded(q, k, v, mesh, causal=True))
    k2 = k.at[:, 12:].set(99.0)
    v2 = v.at[:, 12:].set(-99.0)
    pert = np.asarray(ring_attention_sharded(q, k2, v2, mesh, causal=True))
    np.testing.assert_allclose(pert[:, :12], base[:, :12], atol=1e-5)
    assert not np.allclose(pert[:, 12:], base[:, 12:])


def test_grad_flows(mesh):
    q, k, v = _rand_qkv(jax.random.PRNGKey(3))

    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).max()) > 0


class TestRingFlash:
    """Ring schedule with the Pallas kernel per step (impl="flash")."""

    def test_matches_plain_and_ring(self):
        mesh = make_mesh(dp=2, tp=2, sp=2)
        ks = jax.random.split(jax.random.PRNGKey(11), 3)
        q, k, v = (
            jax.random.normal(kk, (2, 256, 4, 32), jnp.float32) for kk in ks
        )  # T_local = 128: the real kernel path, no fallback
        ref = np.asarray(plain_attention(q, k, v))
        out = np.asarray(ring_attention_sharded(q, k, v, mesh, impl="flash"))
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-3)

    def test_non_causal(self):
        mesh = make_mesh(dp=2, tp=2, sp=2)
        ks = jax.random.split(jax.random.PRNGKey(12), 3)
        q, k, v = (
            jax.random.normal(kk, (2, 128, 4, 32), jnp.float32) for kk in ks
        )
        ref = np.asarray(plain_attention(q, k, v, causal=False))
        out = np.asarray(
            ring_attention_sharded(q, k, v, mesh, causal=False, impl="flash")
        )
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-3)

    def test_gradients_match_plain(self):
        mesh = make_mesh(dp=2, tp=2, sp=2)
        ks = jax.random.split(jax.random.PRNGKey(13), 3)
        q, k, v = (
            jax.random.normal(kk, (2, 256, 4, 32), jnp.float32) for kk in ks
        )
        gf = jax.grad(
            lambda a, b, c: jnp.sum(
                ring_attention_sharded(a, b, c, mesh, impl="flash") ** 2
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        gr = jax.grad(
            lambda a, b, c: jnp.sum(plain_attention(a, b, c) ** 2),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3
            )

    def test_sharded_train_step_ring_flash(self):
        """attn_impl="ring_flash" trains on the 8-device mesh (tiny shards
        use the reference fallback; the path is the same module)."""
        from client_tpu.parallel import named_shardings, param_specs
        from client_tpu.serve.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=64, max_seq=32, dtype="float32",
        )
        mesh = make_mesh(dp=2, tp=2, sp=2)
        params = tfm.init_params(jax.random.PRNGKey(5), cfg)
        params = jax.device_put(params, named_shardings(mesh, param_specs(cfg)))
        opt, step = tfm.make_train_step(
            cfg, mesh=mesh, attn_impl="ring_flash", learning_rate=1e-2
        )
        state = opt.init(params)
        toks = jax.random.randint(jax.random.PRNGKey(6), (4, 17), 0, 64)
        toks = jax.device_put(
            toks,
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("dp", None)),
        )
        first = None
        for _ in range(4):
            params, state, loss = step(params, state, toks)
            if first is None:
                first = float(loss)
        assert float(loss) < first
