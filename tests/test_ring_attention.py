"""Ring attention vs plain attention on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from client_tpu.parallel import make_mesh
from client_tpu.parallel.ring_attention import (
    plain_attention,
    ring_attention_sharded,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=2, tp=2, sp=2)


def _rand_qkv(key, b=2, t=16, h=4, d=8, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, t, h, d), dtype) for k in ks)


def test_ring_matches_plain_causal(mesh):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    expected = plain_attention(q, k, v, causal=True)
    got = ring_attention_sharded(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_ring_matches_plain_noncausal(mesh):
    q, k, v = _rand_qkv(jax.random.PRNGKey(1))
    expected = plain_attention(q, k, v, causal=False)
    got = ring_attention_sharded(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_causality(mesh):
    """Perturbing future tokens must not change earlier outputs."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(2))
    base = np.asarray(ring_attention_sharded(q, k, v, mesh, causal=True))
    k2 = k.at[:, 12:].set(99.0)
    v2 = v.at[:, 12:].set(-99.0)
    pert = np.asarray(ring_attention_sharded(q, k2, v2, mesh, causal=True))
    np.testing.assert_allclose(pert[:, :12], base[:, :12], atol=1e-5)
    assert not np.allclose(pert[:, 12:], base[:, 12:])


def test_grad_flows(mesh):
    q, k, v = _rand_qkv(jax.random.PRNGKey(3))

    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).max()) > 0
