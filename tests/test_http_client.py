"""End-to-end tests: HTTP client against the in-process server.

Covers the capability surface of the reference's HTTP client + examples
(reference tritonclient/http/__init__.py, src/python/examples/simple_http_*).
"""

import numpy as np
import pytest

import client_tpu.http as httpclient
from client_tpu.serve import Server
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def server():
    with Server() as s:
        yield s


@pytest.fixture(scope="module")
def client(server):
    with httpclient.InferenceServerClient(server.http_address, concurrency=4) as c:
        yield c


def _simple_inputs(binary=True):
    inputs = [
        httpclient.InferInput("INPUT0", [1, 16], "INT32"),
        httpclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    i0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    i1 = np.ones((1, 16), dtype=np.int32)
    inputs[0].set_data_from_numpy(i0, binary_data=binary)
    inputs[1].set_data_from_numpy(i1, binary_data=binary)
    return inputs, i0, i1


class TestHealth:
    def test_server_live(self, client):
        assert client.is_server_live()

    def test_server_ready(self, client):
        assert client.is_server_ready()

    def test_model_ready(self, client):
        assert client.is_model_ready("simple")
        assert client.is_model_ready("simple", "1")
        assert not client.is_model_ready("no_such_model")


class TestMetadata:
    def test_server_metadata(self, client):
        meta = client.get_server_metadata()
        assert meta["name"] == "client_tpu.serve"
        assert "binary_tensor_data" in meta["extensions"]
        assert "tpu_shared_memory" in meta["extensions"]

    def test_model_metadata(self, client):
        meta = client.get_model_metadata("simple")
        assert meta["name"] == "simple"
        assert {t["name"] for t in meta["inputs"]} == {"INPUT0", "INPUT1"}
        assert meta["inputs"][0]["datatype"] == "INT32"

    def test_model_config(self, client):
        cfg = client.get_model_config("simple")
        assert cfg["max_batch_size"] == 8
        assert cfg["input"][0]["data_type"] == "TYPE_INT32"

    def test_unknown_model(self, client):
        with pytest.raises(InferenceServerException, match="unknown model"):
            client.get_model_metadata("no_such_model")


class TestInfer:
    def test_binary(self, client):
        inputs, i0, i1 = _simple_inputs()
        outputs = [
            httpclient.InferRequestedOutput("OUTPUT0"),
            httpclient.InferRequestedOutput("OUTPUT1"),
        ]
        result = client.infer("simple", inputs, outputs=outputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), i0 - i1)

    def test_json_mode(self, client):
        inputs, i0, i1 = _simple_inputs(binary=False)
        outputs = [
            httpclient.InferRequestedOutput("OUTPUT0", binary_data=False),
            httpclient.InferRequestedOutput("OUTPUT1", binary_data=False),
        ]
        result = client.infer("simple", inputs, outputs=outputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)
        assert "data" in result.get_output("OUTPUT0")

    def test_no_outputs_requested(self, client):
        inputs, i0, i1 = _simple_inputs()
        result = client.infer("simple", inputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), i0 - i1)

    def test_request_id(self, client):
        inputs, _, _ = _simple_inputs()
        result = client.infer("simple", inputs, request_id="my-req-7")
        assert result.get_response()["id"] == "my-req-7"

    def test_model_version_in_url(self, client):
        inputs, i0, i1 = _simple_inputs()
        result = client.infer("simple", inputs, model_version="1")
        assert result.get_response()["model_version"] == "1"

    def test_bytes_tensor(self, client):
        arr = np.array([b"tpu", b"native", b"client"], dtype=np.object_)
        inp = httpclient.InferInput("INPUT0", [3], "BYTES")
        inp.set_data_from_numpy(arr)
        result = client.infer("identity_bytes", [inp])
        assert list(result.as_numpy("OUTPUT0")) == [b"tpu", b"native", b"client"]

    def test_bytes_json_mode(self, client):
        arr = np.array(["alpha", "beta"], dtype=np.object_)
        inp = httpclient.InferInput("INPUT0", [2], "BYTES")
        inp.set_data_from_numpy(arr, binary_data=False)
        out = httpclient.InferRequestedOutput("OUTPUT0", binary_data=False)
        result = client.infer("identity_bytes", [inp], outputs=[out])
        assert [b.decode() for b in result.as_numpy("OUTPUT0")] == ["alpha", "beta"]

    def test_fp32_identity(self, client):
        arr = np.random.rand(4, 4).astype(np.float32).reshape(16)
        inp = httpclient.InferInput("INPUT0", [16], "FP32")
        inp.set_data_from_numpy(arr)
        result = client.infer("identity", [inp])
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), arr)

    def test_compression_roundtrip(self, client):
        for algo in ("gzip", "deflate"):
            inputs, i0, i1 = _simple_inputs()
            result = client.infer(
                "simple",
                inputs,
                request_compression_algorithm=algo,
                response_compression_algorithm=algo,
            )
            np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)

    def test_classification(self, client):
        x = np.array([[0.1, 3.0, 0.5, 1.0]], dtype=np.float32)
        inp = httpclient.InferInput("INPUT0", [1, 4], "FP32")
        inp.set_data_from_numpy(x)
        out = httpclient.InferRequestedOutput("OUTPUT0", class_count=2)
        result = client.infer("classifier", [inp], outputs=[out])
        top = result.as_numpy("OUTPUT0")
        assert top.shape == (1, 2)
        score, idx, label = top[0][0].decode().split(":")
        assert idx == "1" and label == "dog"

    def test_wrong_dtype_rejected(self, client):
        inp = httpclient.InferInput("INPUT0", [1, 16], "INT32")
        with pytest.raises(InferenceServerException, match="unexpected datatype"):
            inp.set_data_from_numpy(np.zeros((1, 16), dtype=np.float32))

    def test_wrong_shape_rejected(self, client):
        inp = httpclient.InferInput("INPUT0", [1, 16], "INT32")
        with pytest.raises(InferenceServerException, match="unexpected numpy array shape"):
            inp.set_data_from_numpy(np.zeros((2, 16), dtype=np.int32))

    def test_server_side_dtype_error(self, client):
        inp = httpclient.InferInput("INPUT0", [1, 16], "FP32")
        inp.set_data_from_numpy(np.zeros((1, 16), dtype=np.float32))
        inp2 = httpclient.InferInput("INPUT1", [1, 16], "FP32")
        inp2.set_data_from_numpy(np.zeros((1, 16), dtype=np.float32))
        with pytest.raises(InferenceServerException, match="data-type"):
            client.infer("simple", [inp, inp2])

    def test_missing_input(self, client):
        inp = httpclient.InferInput("INPUT0", [1, 16], "INT32")
        inp.set_data_from_numpy(np.zeros((1, 16), dtype=np.int32))
        with pytest.raises(InferenceServerException, match="expected 2 inputs"):
            client.infer("simple", [inp])

    def test_jax_array_input(self, client):
        import jax.numpy as jnp

        arr = jnp.arange(16, dtype=jnp.float32)
        inp = httpclient.InferInput("INPUT0", [16], "FP32")
        inp.set_data_from_array(arr)
        result = client.infer("identity", [inp])
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), np.asarray(arr))


class TestAsyncInfer:
    def test_many_concurrent(self, client):
        handles = []
        for i in range(16):
            inputs, i0, i1 = _simple_inputs()
            handles.append(client.async_infer("simple", inputs, request_id=str(i)))
        for i, h in enumerate(handles):
            result = h.get_result()
            assert result.get_response()["id"] == str(i)

    def test_error_propagates(self, client):
        inputs, _, _ = _simple_inputs()
        handle = client.async_infer("no_such_model", inputs)
        with pytest.raises(InferenceServerException):
            handle.get_result()


class TestPipelining:
    def test_generate_and_parse(self, client, server):
        inputs, i0, i1 = _simple_inputs()
        body, json_size = httpclient.InferenceServerClient.generate_request_body(
            inputs, outputs=[httpclient.InferRequestedOutput("OUTPUT0")]
        )
        assert json_size is not None
        import urllib3

        http = urllib3.PoolManager()
        r = http.request(
            "POST",
            f"http://{server.http_address}/v2/models/simple/infer",
            body=body,
            headers={"Inference-Header-Content-Length": str(json_size)},
        )
        hl = r.headers.get("Inference-Header-Content-Length")
        result = httpclient.InferenceServerClient.parse_response_body(
            r.data, header_length=int(hl) if hl else None
        )
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)


class TestManagement:
    def test_repository_index(self, client):
        index = client.get_model_repository_index()
        names = {m["name"] for m in index}
        assert "simple" in names and "classifier" in names
        assert all(m["state"] == "READY" for m in index)

    def test_load_unload(self, client):
        client.unload_model("identity")
        assert not client.is_model_ready("identity")
        index = client.get_model_repository_index()
        state = {m["name"]: m["state"] for m in index}
        assert state["identity"] == "UNAVAILABLE"
        client.load_model("identity")
        assert client.is_model_ready("identity")

    def test_statistics(self, client):
        inputs, _, _ = _simple_inputs()
        client.infer("simple", inputs)
        stats = client.get_inference_statistics("simple")["model_stats"][0]
        assert stats["name"] == "simple"
        assert stats["inference_count"] >= 1
        assert stats["inference_stats"]["success"]["count"] >= 1
        assert stats["inference_stats"]["compute_infer"]["ns"] > 0

    def test_all_statistics(self, client):
        stats = client.get_inference_statistics()["model_stats"]
        assert len(stats) >= 5

    def test_trace_settings(self, client):
        settings = client.get_trace_settings()
        assert "trace_level" in settings
        updated = client.update_trace_settings(
            settings={"trace_level": ["TIMESTAMPS"], "trace_rate": "500"}
        )
        assert updated["trace_level"] == ["TIMESTAMPS"]
        assert client.get_trace_settings()["trace_rate"] == "500"

    def test_log_settings(self, client):
        updated = client.update_log_settings({"log_verbose_level": 2})
        assert updated["log_verbose_level"] == 2
        assert client.get_log_settings()["log_verbose_level"] == 2

    def test_unknown_endpoint(self, client):
        with pytest.raises(InferenceServerException):
            client._json_or_raise(client._get("v2/bogus"))

    def test_load_with_config_override(self, client):
        client.load_model("identity", config={"max_batch_size": 64})
        cfg = client.get_model_config("identity")
        assert cfg["max_batch_size"] == 64
        client.load_model("identity")  # reload without override resets
        assert client.get_model_config("identity")["max_batch_size"] == 0

    def test_keepalive_survives_error_with_body(self, client):
        # A 400 on a request that carried a body must not desync the pooled
        # connection for the next call.
        with pytest.raises(InferenceServerException, match="CUDA"):
            client.register_cuda_shared_memory("r0", b"\x00" * 16, 0, 64)
        assert client.is_server_live()
        inputs, i0, i1 = _simple_inputs()
        result = client.infer("simple", inputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)

    def test_percent_encoded_model_name(self, client, server):
        from client_tpu.serve.builtins import identity_model

        model = identity_model("weird name/v2", "FP32")
        server.engine.add_model(model)
        assert client.is_model_ready("weird name/v2")
        arr = np.ones(4, dtype=np.float32)
        inp = httpclient.InferInput("INPUT0", [4], "FP32")
        inp.set_data_from_numpy(arr)
        result = client.infer("weird name/v2", [inp])
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), arr)


class TestSequenceHttp:
    def test_stateful_accumulation(self, client):
        def step(value, seq, start=False, end=False):
            inp = httpclient.InferInput("INPUT", [1], "INT32")
            inp.set_data_from_numpy(np.array([value], dtype=np.int32))
            r = client.infer(
                "simple_sequence",
                [inp],
                sequence_id=seq,
                sequence_start=start,
                sequence_end=end,
            )
            return int(r.as_numpy("OUTPUT")[0])

        assert step(10, 42, start=True) == 10
        assert step(5, 42) == 15
        # interleaved different sequence
        assert step(100, 43, start=True) == 100
        assert step(1, 42, end=True) == 16
        # sequence 42 ended; a new start resets
        assert step(2, 42, start=True) == 2


class TestExample:
    def test_simple_http_infer_client(self, server):
        import subprocess
        import sys
        import os

        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(repo, "examples", "simple_http_infer_client.py"),
                "-u",
                server.http_address,
            ],
            capture_output=True,
            text=True,
            env=env,
            timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "PASS" in proc.stdout


class TestTenantPropagation:
    """The tenant= constructor kwarg stamps x-tenant-id on every verb so
    callers stop hand-threading headers= through each call."""

    def test_tenant_kwarg_stamps_every_verb(self):
        from client_tpu.serve.frontdoor import TenantQoS

        qos = TenantQoS()
        with Server(qos=qos) as server:
            with httpclient.InferenceServerClient(
                server.http_address, tenant="acme"
            ) as client:
                assert client.is_server_ready()  # probe verbs stamped too
                inputs, i0, i1 = _simple_inputs()
                result = client.infer("simple", inputs)
                np.testing.assert_array_equal(
                    result.as_numpy("OUTPUT0"), i0 + i1
                )
            snapshot = qos.snapshot()
            assert "acme" in snapshot
            assert snapshot["acme"]["requests"] >= 1

    def test_explicit_header_wins_over_tenant_kwarg(self):
        from client_tpu.serve.frontdoor import TenantQoS

        qos = TenantQoS()
        with Server(qos=qos) as server:
            with httpclient.InferenceServerClient(
                server.http_address, tenant="acme"
            ) as client:
                inputs, _, _ = _simple_inputs()
                client.infer(
                    "simple", inputs, headers={"X-Tenant-Id": "override"}
                )
            snapshot = qos.snapshot()
            assert "override" in snapshot and "acme" not in snapshot

    def test_aio_tenant_kwarg(self):
        import asyncio

        import client_tpu.http.aio as aioclient
        from client_tpu.serve.frontdoor import TenantQoS

        qos = TenantQoS()
        with Server(qos=qos) as server:

            async def run():
                async with aioclient.InferenceServerClient(
                    server.http_address, tenant="aio-acme"
                ) as client:
                    inputs, _, _ = _simple_inputs()
                    await client.infer("simple", inputs)

            asyncio.run(run())
            assert "aio-acme" in qos.snapshot()
