"""End-to-end tracing + metrics surface (the observability layer).

Covers:
- the full /metrics payload through a small text-exposition parser
  (HELP/TYPE for every sample family, label escaping for hostile model
  names, monotonic counters across requests, histograms, gauges),
- one traced inference through each frontend producing client + server
  spans under a single shared trace id with properly ordered timestamps,
- trace_rate sampling, trace_count exhaustion, and the disabled default
  (no trace file, no samples),
- trace_settings schema fidelity over both protocols,
- resilience instrumentation: shed/drain counters, retry attempt spans,
  and the RetryPolicy/CircuitBreaker observer hooks feeding the registry.
"""

import json
import re
import time
import urllib.request

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
import client_tpu.http as httpclient
from client_tpu import resilience
from client_tpu.serve import Model, Server, TensorSpec
from client_tpu.serve.metrics import (
    Registry,
    ResilienceMetricsObserver,
    escape_label,
    render_metrics,
)
from client_tpu.tracing import ClientTracer, parse_traceparent, read_trace_file
from client_tpu.utils import InferenceServerException

NASTY = 'evil"model\\rogue'  # quote + backslash in a label value


def _nasty_model():
    def fn(inputs, params, ctx):
        return {"OUT": inputs["IN"]}

    return Model(
        NASTY,
        inputs=[TensorSpec("IN", "FP32", [-1])],
        outputs=[TensorSpec("OUT", "FP32", [-1])],
        fn=fn,
    )


def _infer_simple(client, n=1):
    inputs = [
        httpclient.InferInput("INPUT0", [1, 16], "INT32"),
        httpclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(np.ones((1, 16), np.int32))
    inputs[1].set_data_from_numpy(np.ones((1, 16), np.int32))
    for _ in range(n):
        client.infer("simple", inputs)


def _grpc_infer_simple(client, n=1):
    inputs = [
        grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
        grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(np.ones((1, 16), np.int32))
    inputs[1].set_data_from_numpy(np.ones((1, 16), np.int32))
    for _ in range(n):
        client.infer("simple", inputs)


# -- exposition-format parser ----------------------------------------------

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value):
    return (
        value.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def parse_exposition(text):
    """Prometheus text format -> {family: {help, type, samples}} where
    samples is a list of (sample_name, labels_dict, float_value)."""
    meta = {}
    samples = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, help_ = line[len("# HELP "):].split(" ", 1)
            meta.setdefault(name, {})["help"] = help_
            continue
        if line.startswith("# TYPE "):
            name, type_ = line[len("# TYPE "):].split(" ", 1)
            meta.setdefault(name, {})["type"] = type_
            continue
        assert not line.startswith("#"), f"unknown comment line: {line!r}"
        name_part, _, value_part = line.rpartition(" ")
        value = float(value_part)  # malformed lines fail loudly here
        if "{" in name_part:
            name, labels_part = name_part.split("{", 1)
            assert labels_part.endswith("}"), f"unterminated labels: {line!r}"
            labels = {
                k: _unescape(v)
                for k, v in _LABEL_RE.findall(labels_part[:-1])
            }
        else:
            name, labels = name_part, {}
        samples.append((name, labels, value))
    families = {}
    for name, labels, value in samples:
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in meta:
                family = name[: -len(suffix)]
                break
        families.setdefault(family, {"samples": []})
        families[family]["samples"].append((name, labels, value))
    for family, info in families.items():
        info.update(meta.get(family, {}))
    return families


def _scrape(server):
    url = f"http://{server.http_address}/metrics"
    return urllib.request.urlopen(url).read().decode()


class TestMetricsSurface:
    @pytest.fixture(scope="class")
    def server(self):
        with Server(models=[_nasty_model()], http_port=0, grpc_port=0) as s:
            yield s

    def test_every_sample_family_has_help_and_type(self, server):
        families = parse_exposition(_scrape(server))
        assert families  # payload is non-trivial
        for family, info in families.items():
            assert info.get("help"), f"{family} missing # HELP"
            assert info.get("type"), f"{family} missing # TYPE"

    def test_families_are_contiguous(self, server):
        """All samples of one family form a single block — the exposition
        format forbids interleaving families (family-keyed parsers drop or
        reject split groups)."""
        import itertools

        text = _scrape(server)
        meta = {
            line.split(" ", 3)[2]
            for line in text.splitlines()
            if line.startswith("# TYPE ")
        }

        def family_of(sample_name):
            for suffix in ("_bucket", "_sum", "_count"):
                if (
                    sample_name.endswith(suffix)
                    and sample_name[: -len(suffix)] in meta
                ):
                    return sample_name[: -len(suffix)]
            return sample_name

        seq = [
            family_of(line.split("{")[0].split(" ")[0])
            for line in text.splitlines()
            if line.strip() and not line.startswith("#")
        ]
        runs = [k for k, _ in itertools.groupby(seq)]
        assert len(runs) == len(set(runs)), (
            f"interleaved metric families: {runs}"
        )

    def test_histogram_gauge_counter_series_present(self, server):
        families = parse_exposition(_scrape(server))
        assert families["ctpu_request_duration_us"]["type"] == "histogram"
        assert families["ctpu_queue_duration_us"]["type"] == "histogram"
        assert families["ctpu_batch_size"]["type"] == "histogram"
        assert families["ctpu_inflight_requests"]["type"] == "gauge"
        assert families["ctpu_draining"]["type"] == "gauge"
        assert families["ctpu_inference_request_success"]["type"] == "counter"
        # the fail-side and per-phase cumulative series reach /metrics
        for name in (
            "ctpu_inference_fail_duration_us",
            "ctpu_inference_queue_duration_us",
            "ctpu_inference_compute_input_duration_us",
            "ctpu_inference_compute_infer_duration_us",
            "ctpu_inference_compute_output_duration_us",
        ):
            assert families[name]["type"] == "counter"
            assert families[name]["samples"]

    def test_label_escaping_round_trips_hostile_model_name(self, server):
        text = _scrape(server)
        # escaped on the wire ...
        assert escape_label(NASTY) in text
        assert NASTY not in text.replace(escape_label(NASTY), "")
        # ... and the parser recovers the original name from every family
        families = parse_exposition(text)
        success = families["ctpu_inference_request_success"]["samples"]
        assert any(labels.get("model") == NASTY for _, labels, _ in success)
        buckets = families["ctpu_request_duration_us"]["samples"]
        assert any(labels.get("model") == NASTY for _, labels, _ in buckets)

    def test_counters_and_histograms_monotonic_across_requests(self, server):
        def snapshot():
            families = parse_exposition(_scrape(server))

            def value(family, name_suffix=""):
                return sum(
                    v
                    for name, labels, v in families[family]["samples"]
                    if labels.get("model") == "simple"
                    and name.endswith(name_suffix)
                )

            return (
                value("ctpu_inference_request_success"),
                value("ctpu_request_duration_us", "_count"),
                value("ctpu_request_duration_us", "_sum"),
            )

        before = snapshot()
        with httpclient.InferenceServerClient(server.http_address) as c:
            _infer_simple(c, n=3)
        after = snapshot()
        assert after[0] - before[0] == 3
        assert after[1] - before[1] == 3
        assert after[2] > before[2]

    def test_failure_series_accumulate(self, server):
        families = parse_exposition(_scrape(server))

        def fail_count():
            return sum(
                v
                for _, labels, v in parse_exposition(_scrape(server))[
                    "ctpu_inference_request_failure"
                ]["samples"]
                if labels.get("model") == "simple"
            )

        del families
        before = fail_count()
        with httpclient.InferenceServerClient(server.http_address) as c:
            inputs = [httpclient.InferInput("INPUT0", [1, 16], "INT32")]
            inputs[0].set_data_from_numpy(np.ones((1, 16), np.int32))
            with pytest.raises(InferenceServerException):
                c.infer("simple", inputs)  # missing INPUT1
        assert fail_count() == before + 1

    def test_metrics_manager_scrapes_new_series(self, server):
        from client_tpu.perf.metrics_manager import MetricsManager

        with httpclient.InferenceServerClient(server.http_address) as c:
            _infer_simple(c, n=2)
        mm = MetricsManager(f"http://{server.http_address}/metrics")
        first = mm.scrape()
        with httpclient.InferenceServerClient(server.http_address) as c:
            _infer_simple(c, n=4)
        last = mm.scrape()
        assert "ctpu_inference_compute_infer_duration_us" in last
        assert "ctpu_request_duration_us_count" in last
        breakdown = MetricsManager.server_breakdown([first, last])
        assert "ctpu_server_compute_infer_us_per_infer" in breakdown
        assert breakdown["ctpu_server_compute_infer_us_per_infer"]["avg"] >= 0
        # summarize() folds the breakdown into the per-window summary the
        # perf report renders
        summary = MetricsManager.summarize([first, last])
        assert "ctpu_server_queue_us_per_infer" in summary


class TestShedAndDrainCounters:
    def test_overload_shed_counter(self):
        with Server(http_port=0, max_inflight=0) as s:
            with httpclient.InferenceServerClient(s.http_address) as c:
                with pytest.raises(InferenceServerException):
                    _infer_simple(c)
            families = parse_exposition(_scrape(s))
            sheds = families["ctpu_requests_shed_total"]["samples"]
            assert any(
                labels.get("reason") == "overload" and v >= 1
                for _, labels, v in sheds
            )

    def test_drain_flips_gauge_and_counts(self):
        s = Server(http_port=0).start()
        try:
            assert s.engine.drain(timeout_s=5.0)
            text = render_metrics(s.engine)
            families = parse_exposition(text)
            assert families["ctpu_draining"]["samples"][0][2] == 1
            drains = families["ctpu_drain_total"]["samples"]
            assert drains and drains[0][2] >= 1
        finally:
            s.stop()


class TestResilienceObservers:
    def test_retry_observer_counts_backoffs_and_giveup(self):
        registry = Registry()
        obs = ResilienceMetricsObserver("ep1", registry=registry)
        policy = resilience.RetryPolicy(
            max_attempts=3, initial_backoff_s=0.001, jitter=False,
            observer=obs,
        )

        def always_503(_timeout):
            raise InferenceServerException("overloaded", status="503")

        with pytest.raises(InferenceServerException):
            resilience.call_with_retry(always_503, policy)
        assert registry.get(
            "ctpu_client_retries_total", {"endpoint": "ep1"}
        ) == 2  # 3 attempts = 2 backoffs
        assert registry.get(
            "ctpu_client_request_failures_total", {"endpoint": "ep1"}
        ) == 1

    def test_circuit_observer_tracks_state_gauge(self):
        registry = Registry()
        obs = ResilienceMetricsObserver("ep2", registry=registry)
        breaker = resilience.CircuitBreaker(
            failure_threshold=2, reset_timeout_s=60.0, observer=obs
        )
        state = lambda: registry.get(  # noqa: E731 - tiny accessor
            "ctpu_client_circuit_state", {"endpoint": "ep2"}
        )
        assert state() == 0  # closed at registration
        breaker.record_failure()
        assert state() == 0
        breaker.record_failure()  # threshold reached -> open
        assert state() == 2
        assert registry.get(
            "ctpu_client_circuit_transitions_total",
            {"endpoint": "ep2", "to": "open"},
        ) == 1
        breaker.record_success()
        assert state() == 0


class TestEndToEndTracing:
    def _enable(self, server, trace_file, **overrides):
        settings = {
            "trace_level": ["TIMESTAMPS"],
            "trace_rate": "1",
            "trace_count": "-1",
            "trace_file": trace_file,
        }
        settings.update(overrides)
        with httpclient.InferenceServerClient(server.http_address) as c:
            c.update_trace_settings(settings=settings)

    @staticmethod
    def _by_name(record):
        return {t["name"]: t["ns"] for t in record["timestamps"]}

    def _assert_joined(self, records):
        """One shared trace id; client attempt brackets the server span;
        server queue -> compute timestamps properly ordered."""
        assert {r["trace_id"] for r in records} == {
            records[0]["trace_id"]
        }
        client = next(r for r in records if r["source"] == "client")
        server = next(r for r in records if r["source"] == "server")
        # the traceparent the client propagated is the server's parent span
        assert server["parent_span_id"] == client["span_id"]
        ct = self._by_name(client)
        st = self._by_name(server)
        assert ct["CLIENT_REQUEST_START"] <= ct["CLIENT_ATTEMPT_START"]
        assert ct["CLIENT_ATTEMPT_START"] <= st["REQUEST_START"]
        assert (
            st["REQUEST_START"]
            <= st["QUEUE_START"]
            <= st["QUEUE_END"]
            <= st["COMPUTE_START"]
            <= st["COMPUTE_END"]
        )
        assert st["COMPUTE_END"] <= ct["CLIENT_REQUEST_END"]

    def test_http_infer_joins_client_and_server_spans(self, tmp_path):
        trace_file = str(tmp_path / "trace.jsonl")
        with Server(http_port=0) as s:
            self._enable(s, trace_file)
            tracer = ClientTracer(trace_file=trace_file)
            with httpclient.InferenceServerClient(
                s.http_address, tracer=tracer
            ) as c:
                _infer_simple(c)
        records = read_trace_file(trace_file)
        assert len(records) == 2
        assert {r["source"] for r in records} == {"client", "server"}
        self._assert_joined(records)
        server = next(r for r in records if r["source"] == "server")
        assert server["protocol"] == "http"
        assert server["model_name"] == "simple"

    def test_grpc_infer_joins_client_and_server_spans(self, tmp_path):
        trace_file = str(tmp_path / "trace.jsonl")
        with Server(http_port=0, grpc_port=0) as s:
            self._enable(s, trace_file)
            tracer = ClientTracer(trace_file=trace_file)
            with grpcclient.InferenceServerClient(
                s.grpc_address, tracer=tracer
            ) as c:
                _grpc_infer_simple(c)
        records = read_trace_file(trace_file)
        assert len(records) == 2
        self._assert_joined(records)
        server = next(r for r in records if r["source"] == "server")
        assert server["protocol"] == "grpc"

    def test_trace_rate_samples_first_of_every_n(self, tmp_path):
        trace_file = str(tmp_path / "trace.jsonl")
        with Server(http_port=0) as s:
            self._enable(s, trace_file, trace_rate="3")
            with httpclient.InferenceServerClient(s.http_address) as c:
                _infer_simple(c, n=6)
        records = read_trace_file(trace_file)
        assert len(records) == 2  # requests 1 and 4 of 6

    def test_trace_count_budget_exhausts(self, tmp_path):
        trace_file = str(tmp_path / "trace.jsonl")
        with Server(http_port=0) as s:
            self._enable(s, trace_file, trace_count="1")
            with httpclient.InferenceServerClient(s.http_address) as c:
                _infer_simple(c, n=3)
            assert len(read_trace_file(trace_file)) == 1
            # updating trace_count restarts the budget
            self._enable(s, trace_file, trace_count="1")
            with httpclient.InferenceServerClient(s.http_address) as c:
                _infer_simple(c, n=2)
        assert len(read_trace_file(trace_file)) == 2

    def test_failed_request_records_error_on_both_spans(self, tmp_path):
        trace_file = str(tmp_path / "trace.jsonl")
        with Server(http_port=0) as s:
            self._enable(s, trace_file)
            tracer = ClientTracer(trace_file=trace_file)
            with httpclient.InferenceServerClient(
                s.http_address, tracer=tracer
            ) as c:
                inputs = [httpclient.InferInput("INPUT0", [1, 16], "INT32")]
                inputs[0].set_data_from_numpy(np.ones((1, 16), np.int32))
                with pytest.raises(InferenceServerException):
                    c.infer("simple", inputs)  # missing INPUT1
        records = read_trace_file(trace_file)
        assert len(records) == 2
        for record in records:
            assert "INPUT1" in record.get("error", ""), record

    def test_tracing_disabled_by_default_writes_nothing(self, tmp_path):
        trace_file = tmp_path / "trace.jsonl"
        with Server(http_port=0) as s:
            with httpclient.InferenceServerClient(s.http_address) as c:
                _infer_simple(c, n=2)
            assert not s.engine.tracer.completed
        assert not trace_file.exists()

    def test_retry_attempts_join_one_trace(self, tmp_path):
        """A shed-then-retried request shows BOTH attempts as client spans
        under the same trace id, plus the server span of the attempt that
        landed; the shed is counted in /metrics."""
        trace_file = str(tmp_path / "trace.jsonl")
        with Server(http_port=0) as s:
            self._enable(s, trace_file)
            s.engine.max_inflight = 0  # next request is shed (503)

            class _Unshed:
                def on_backoff(self, attempt, delay_s, exc):
                    s.engine.max_inflight = None  # recover before the retry

            policy = resilience.RetryPolicy(
                max_attempts=3, initial_backoff_s=0.01, jitter=False,
                observer=_Unshed(),
            )
            tracer = ClientTracer(trace_file=trace_file)
            with httpclient.InferenceServerClient(
                s.http_address, retry_policy=policy, tracer=tracer
            ) as c:
                _infer_simple(c)
            families = parse_exposition(_scrape(s))
            sheds = families["ctpu_requests_shed_total"]["samples"]
            assert any(
                labels.get("reason") == "overload" and v >= 1
                for _, labels, v in sheds
            )
        records = read_trace_file(trace_file)
        client = next(r for r in records if r["source"] == "client")
        attempts = [
            t for t in client["timestamps"]
            if t["name"] == "CLIENT_ATTEMPT_START"
        ]
        assert len(attempts) == 2  # the shed attempt + the one that landed
        # both server-side samples (shed requests are not traced past the
        # frontend? they ARE: sampled before execute) share the trace id
        assert {r["trace_id"] for r in records} == {client["trace_id"]}


class TestTraceSettingsFidelity:
    def test_settings_round_trip_identically_over_both_protocols(self):
        with Server(http_port=0, grpc_port=0) as s:
            with httpclient.InferenceServerClient(s.http_address) as hc:
                # ints and bare strings normalize to the canonical schema
                updated = hc.update_trace_settings(
                    settings={"trace_rate": 250, "trace_level": "timestamps"}
                )
                assert updated["trace_rate"] == "250"
                assert updated["trace_level"] == ["TIMESTAMPS"]
                http_view = hc.get_trace_settings()
            with grpcclient.InferenceServerClient(s.grpc_address) as gc:
                response = gc.get_trace_settings()
                grpc_view = {
                    key: list(value.value)
                    for key, value in response.settings.items()
                }
            # identical values over both protocols (gRPC's wire type is
            # list-of-string for every setting; trace_level IS the list)
            assert grpc_view["trace_level"] == http_view["trace_level"]
            assert grpc_view["trace_rate"] == [http_view["trace_rate"]]
            assert grpc_view["trace_count"] == [http_view["trace_count"]]
            # and a gRPC update is visible identically over HTTP
            with grpcclient.InferenceServerClient(s.grpc_address) as gc:
                gc.update_trace_settings(
                    settings={"trace_rate": 99, "trace_level": ["TENSORS"]}
                )
            with httpclient.InferenceServerClient(s.http_address) as hc:
                got = hc.get_trace_settings()
            assert got["trace_rate"] == "99"
            assert got["trace_level"] == ["TENSORS"]

    def test_malformed_settings_rejected_over_both_protocols(self):
        with Server(http_port=0, grpc_port=0) as s:
            with httpclient.InferenceServerClient(s.http_address) as hc:
                with pytest.raises(InferenceServerException):
                    hc.update_trace_settings(settings={"trace_rate": "lots"})
                with pytest.raises(InferenceServerException):
                    hc.update_trace_settings(
                        settings={"trace_level": ["LOUD"]}
                    )
            with grpcclient.InferenceServerClient(s.grpc_address) as gc:
                with pytest.raises(InferenceServerException):
                    gc.update_trace_settings(settings={"trace_rate": "lots"})
            # a rejected update leaves the settings untouched
            with httpclient.InferenceServerClient(s.http_address) as hc:
                assert hc.get_trace_settings()["trace_rate"] == "1000"


class TestTraceparentHelpers:
    def test_parse_round_trip(self):
        tracer = ClientTracer()
        trace = tracer.sample("m")
        parsed = parse_traceparent(trace.traceparent())
        assert parsed == (trace.trace_id, trace.span_id)

    def test_malformed_headers_are_ignored(self):
        for bad in ("", None, "zz", "00-short-span-01", "oo-" + "0" * 53):
            assert parse_traceparent(bad) is None


class TestSloAndFlightSurface:
    """The SLO watchdog + flight recorder as wired into a real server:
    gauges on /metrics, the debug endpoints, breach-triggered dumps, and
    the metrics-manager prefix audit."""

    def test_slo_gauges_reach_metrics_endpoint(self):
        with Server(http_port=0) as server:
            with httpclient.InferenceServerClient(server.http_address) as c:
                _infer_simple(c, n=4)
            server.engine.slo.check_now()
            families = parse_exposition(_scrape(server))
            assert "ctpu_slo_p99_ms" in families
            samples = families["ctpu_slo_p99_ms"]["samples"]
            assert any(
                labels.get("model") == "simple" and value > 0
                for _n, labels, value in samples
            )
            assert "ctpu_slo_error_rate" in families

    def test_slo_debug_endpoint(self):
        with Server(http_port=0) as server:
            with httpclient.InferenceServerClient(server.http_address) as c:
                _infer_simple(c, n=2)
            body = urllib.request.urlopen(
                f"http://{server.http_address}/v2/debug/slo"
            ).read()
            summary = json.loads(body)
            assert summary["simple|"]["count"] == 2

    def test_flight_debug_endpoint_serves_ring(self, tmp_path):
        with Server(http_port=0) as server:
            server.engine.update_trace_settings({
                "trace_level": ["TIMESTAMPS"], "trace_rate": "1",
            })
            with httpclient.InferenceServerClient(server.http_address) as c:
                _infer_simple(c, n=1)
            # the span reaches the ring when the handler COMPLETES the
            # trace, after the response is sent — poll instead of racing
            # the handler's final write
            deadline = time.monotonic() + 2.0
            while True:
                body = urllib.request.urlopen(
                    f"http://{server.http_address}/v2/debug/flight"
                ).read().decode()
                lines = [json.loads(line) for line in body.splitlines()]
                if any(r["kind"] == "span" for r in lines[1:]) \
                        or time.monotonic() > deadline:
                    break
                time.sleep(0.02)
            assert lines[0]["kind"] == "flight_dump"
            assert lines[0]["reason"] == "debug_endpoint"
            assert any(r["kind"] == "span" for r in lines[1:])

    def test_induced_breach_counts_and_dumps(self, tmp_path):
        """The acceptance bullet: an induced p99 breach produces a
        flight-recorder dump plus ctpu_slo_breaches_total >= 1."""
        from client_tpu.serve.flight import FlightRecorder
        from client_tpu.serve.slo import SloWatchdog

        def slow_fn(inputs, params, ctx):
            time.sleep(0.02)  # 20ms against a 1ms objective
            return {"OUT": inputs["IN"]}

        slow = Model(
            "slow",
            inputs=[TensorSpec("IN", "FP32", [-1])],
            outputs=[TensorSpec("OUT", "FP32", [-1])],
            fn=slow_fn,
        )
        watchdog = SloWatchdog(
            objectives={"slow": {"p99_ms": 1.0}},
            min_samples=4, check_every=4, dump_interval_s=0.0,
        )
        with Server(models=[slow], with_default_models=False,
                    http_port=0, slo=watchdog) as server:
            server.engine.flight.dump_dir = str(tmp_path)
            with httpclient.InferenceServerClient(server.http_address) as c:
                inp = httpclient.InferInput("IN", [4], "FP32")
                inp.set_data_from_numpy(np.ones(4, np.float32))
                for _ in range(8):
                    c.infer("slow", [inp])
            families = parse_exposition(_scrape(server))
            assert "ctpu_slo_breaches_total" in families
            total = sum(
                value
                for _n, labels, value in
                families["ctpu_slo_breaches_total"]["samples"]
                if labels.get("model") == "slow"
            )
            assert total >= 1
            dumps = list(tmp_path.glob("flight-*-slo_breach.jsonl"))
            assert dumps, "breach produced no flight dump"
            lines = [json.loads(line) for line in open(dumps[0])]
            assert any(r["kind"] == "slo_breach" for r in lines[1:])
            assert "ctpu_flight_dumps_total" in families

    def test_4xx_is_not_an_slo_error(self):
        with Server(http_port=0) as server:
            client = httpclient.InferenceServerClient(server.http_address)
            with pytest.raises(InferenceServerException):
                client.infer("no_such_model", [])
            client.close()
            summary = server.engine.slo.check_now()
            entry = summary.get("no_such_model|")
            assert entry is not None and entry["error_rate"] == 0.0

    def test_metrics_manager_summarizes_prefixed_series(self):
        from client_tpu.perf.metrics_manager import MetricsManager

        first = {
            "ctpu_slo_p99_ms": [('{model="m",tenant=""}', 12.0)],
            "ctpu_fleet_peer_hits_total": [('{op="prefix"}', 3.0)],
            "ctpu_lm_kv_blocks_used": [("", 7.0)],
        }
        last = {
            "ctpu_slo_p99_ms": [('{model="m",tenant=""}', 16.0)],
            "ctpu_fleet_peer_hits_total": [('{op="prefix"}', 9.0)],
            "ctpu_lm_kv_blocks_used": [("", 5.0)],
        }
        summary = MetricsManager.summarize([first, last])
        assert summary["ctpu_slo_p99_ms"] == {"avg": 14.0, "max": 16.0}
        # counters report the window delta
        assert summary["ctpu_fleet_peer_hits_total"]["avg"] == 6.0
        assert summary["ctpu_lm_kv_blocks_used"]["max"] == 7.0

    def test_quantile_and_rate_gauges_fold_by_max_not_sum(self):
        """Two models' p99s must NOT sum into a latency nobody saw (and
        summed error rates would exceed 1.0) — non-additive gauges take
        the worst label set per snapshot."""
        from client_tpu.perf.metrics_manager import MetricsManager

        snap = {
            "ctpu_slo_p99_ms": [
                ('{model="a",tenant=""}', 100.0),
                ('{model="b",tenant=""}', 400.0),
            ],
            "ctpu_slo_error_rate": [
                ('{model="a",tenant=""}', 0.5),
                ('{model="b",tenant=""}', 0.5),
            ],
            "ctpu_lm_kv_blocks_used": [("", 3.0), ("", 4.0)],
        }
        summary = MetricsManager.summarize([snap])
        assert summary["ctpu_slo_p99_ms"]["max"] == 400.0
        assert summary["ctpu_slo_error_rate"]["max"] == 0.5
        # usage gauges still fold additively across label sets
        assert summary["ctpu_lm_kv_blocks_used"]["max"] == 7.0
