"""End-to-end tests: gRPC client against the in-process gRPC frontend.

Covers the reference's gRPC surface (tritonclient/grpc) incl. streaming
sequence workloads (reference simple_grpc_sequence_stream_infer_client) and
decoupled models.
"""

import queue

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
from client_tpu.serve import Server
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def server():
    with Server(grpc_port=0) as s:
        yield s


@pytest.fixture()
def client(server):
    with grpcclient.InferenceServerClient(server.grpc_address) as c:
        yield c


def _simple_inputs():
    inputs = [
        grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
        grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    i0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    i1 = np.full((1, 16), 2, dtype=np.int32)
    inputs[0].set_data_from_numpy(i0)
    inputs[1].set_data_from_numpy(i1)
    return inputs, i0, i1


class TestHealthMetadata:
    def test_health(self, client):
        assert client.is_server_live()
        assert client.is_server_ready()
        assert client.is_model_ready("simple")
        assert not client.is_model_ready("nope")

    def test_server_metadata(self, client):
        meta = client.get_server_metadata()
        assert meta.name == "client_tpu.serve"
        meta_json = client.get_server_metadata(as_json=True)
        assert "tpu_shared_memory" in meta_json["extensions"]

    def test_model_metadata(self, client):
        meta = client.get_model_metadata("simple")
        assert meta.name == "simple"
        assert meta.inputs[0].datatype == "INT32"
        assert list(meta.inputs[0].shape) == [-1, 16]

    def test_model_config_proto(self, client):
        from client_tpu._proto import model_config_pb2 as mc

        cfg = client.get_model_config("simple").config
        assert cfg.max_batch_size == 8
        assert cfg.input[0].data_type == mc.TYPE_INT32
        decoupled = client.get_model_config("repeat_int32").config
        assert decoupled.model_transaction_policy.decoupled

    def test_error_status(self, client):
        with pytest.raises(InferenceServerException) as e:
            client.get_model_metadata("nope")
        assert e.value.status() == "INVALID_ARGUMENT"


class TestInfer:
    def test_infer(self, client):
        inputs, i0, i1 = _simple_inputs()
        result = client.infer("simple", inputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), i0 - i1)

    def test_requested_output_subset(self, client):
        inputs, i0, i1 = _simple_inputs()
        result = client.infer(
            "simple", inputs, outputs=[grpcclient.InferRequestedOutput("OUTPUT1")]
        )
        assert result.as_numpy("OUTPUT0") is None
        np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), i0 - i1)

    def test_request_id_and_version(self, client):
        inputs, _, _ = _simple_inputs()
        result = client.infer(
            "simple", inputs, model_version="1", request_id="rq1"
        )
        assert result.get_response().id == "rq1"
        assert result.get_response().model_version == "1"

    def test_bytes_roundtrip(self, client):
        arr = np.array([b"grpc", b"bytes"], dtype=np.object_)
        inp = grpcclient.InferInput("INPUT0", [2], "BYTES")
        inp.set_data_from_numpy(arr)
        result = client.infer("identity_bytes", [inp])
        assert list(result.as_numpy("OUTPUT0")) == [b"grpc", b"bytes"]

    def test_classification(self, client):
        x = np.array([[0.1, 3.0, 0.5, 1.0]], dtype=np.float32)
        inp = grpcclient.InferInput("INPUT0", [1, 4], "FP32")
        inp.set_data_from_numpy(x)
        out = grpcclient.InferRequestedOutput("OUTPUT0", class_count=2)
        result = client.infer("classifier", [inp], outputs=[out])
        top = result.as_numpy("OUTPUT0")
        assert top.shape == (1, 2)
        assert top[0][0].decode().split(":")[1:] == ["1", "dog"]

    def test_compression(self, client):
        inputs, i0, i1 = _simple_inputs()
        result = client.infer("simple", inputs, compression_algorithm="gzip")
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)

    def test_decoupled_unary_rejected(self, client):
        inp = grpcclient.InferInput("IN", [1], "INT32")
        inp.set_data_from_numpy(np.array([2], dtype=np.int32))
        with pytest.raises(InferenceServerException, match="decoupled"):
            client.infer("repeat_int32", [inp])

    def test_custom_parameters(self, client):
        inputs, i0, i1 = _simple_inputs()
        result = client.infer("simple", inputs, parameters={"my_param": "x"})
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)

    def test_reserved_parameter_rejected(self, client):
        inputs, _, _ = _simple_inputs()
        with pytest.raises(InferenceServerException, match="reserved"):
            client.infer("simple", inputs, parameters={"sequence_id": 1})


class TestAsyncInfer:
    def test_callback(self, client):
        results = queue.Queue()
        inputs, i0, i1 = _simple_inputs()
        client.async_infer(
            "simple",
            inputs,
            callback=lambda result, error: results.put((result, error)),
        )
        result, error = results.get(timeout=10)
        assert error is None
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)

    def test_callback_error(self, client):
        results = queue.Queue()
        inputs, _, _ = _simple_inputs()
        client.async_infer(
            "nope",
            inputs,
            callback=lambda result, error: results.put((result, error)),
        )
        result, error = results.get(timeout=10)
        assert result is None
        assert isinstance(error, InferenceServerException)
        assert error.status() == "INVALID_ARGUMENT"


class TestStreaming:
    def test_two_sequences_one_stream(self, client):
        """Parity scenario: reference
        simple_grpc_sequence_stream_infer_client.cc:96-136 drives two
        stateful sequences concurrently on one bidi stream."""
        results = queue.Queue()
        client.start_stream(
            callback=lambda result, error: results.put((result, error))
        )
        values = [11, 7, 5, 3, 2, 0, 1]

        def send(value, seq, start=False, end=False):
            inp = grpcclient.InferInput("INPUT", [1], "INT32")
            inp.set_data_from_numpy(np.array([value], dtype=np.int32))
            client.async_stream_infer(
                "simple_sequence",
                [inp],
                request_id=f"{seq}_{value}",
                sequence_id=seq,
                sequence_start=start,
                sequence_end=end,
            )

        for i, v in enumerate(values):
            send(v, 1001, start=(i == 0), end=(i == len(values) - 1))
            send(-v, 1002, start=(i == 0), end=(i == len(values) - 1))

        seq_results = {1001: [], 1002: []}
        for _ in range(2 * len(values)):
            result, error = results.get(timeout=15)
            assert error is None
            rid = result.get_response().id
            seq = int(rid.split("_")[0])
            seq_results[seq].append(int(result.as_numpy("OUTPUT")[0]))
        client.stop_stream()
        expected = list(np.cumsum(values))
        assert seq_results[1001] == expected
        assert seq_results[1002] == [-v for v in expected]

    def test_decoupled_stream(self, client):
        results = queue.Queue()
        client.start_stream(
            callback=lambda result, error: results.put((result, error))
        )
        inp = grpcclient.InferInput("IN", [1], "INT32")
        inp.set_data_from_numpy(np.array([5], dtype=np.int32))
        client.async_stream_infer("repeat_int32", [inp])
        got = []
        for _ in range(5):
            result, error = results.get(timeout=15)
            assert error is None
            got.append(int(result.as_numpy("OUT")[0]))
        client.stop_stream()
        assert got == [0, 1, 2, 3, 4]

    def test_stream_error_reported(self, client):
        results = queue.Queue()
        client.start_stream(
            callback=lambda result, error: results.put((result, error))
        )
        inputs, _, _ = _simple_inputs()
        # unknown model inside the stream -> error via callback, stream survives
        for inp in inputs:
            pass
        client.async_stream_infer("nope", inputs)
        result, error = results.get(timeout=15)
        assert error is not None
        client.stop_stream()

    def test_double_start_rejected(self, client):
        client.start_stream(callback=lambda result, error: None)
        with pytest.raises(InferenceServerException, match="already active"):
            client.start_stream(callback=lambda result, error: None)
        client.stop_stream()


class TestManagement:
    def test_repository(self, client):
        index = client.get_model_repository_index()
        names = {m.name for m in index.models}
        assert "simple" in names
        client.unload_model("identity")
        assert not client.is_model_ready("identity")
        client.load_model("identity")
        assert client.is_model_ready("identity")

    def test_load_with_config(self, client):
        client.load_model("identity", config={"max_batch_size": 16})
        assert client.get_model_config("identity").config.max_batch_size == 16
        client.load_model("identity")

    def test_statistics(self, client):
        inputs, _, _ = _simple_inputs()
        client.infer("simple", inputs)
        stats = client.get_inference_statistics("simple")
        entry = stats.model_stats[0]
        assert entry.name == "simple"
        assert entry.inference_count >= 1
        assert entry.inference_stats.success.count >= 1

    def test_trace_settings(self, client):
        settings = client.get_trace_settings()
        assert "trace_level" in settings.settings
        updated = client.update_trace_settings(
            settings={"trace_rate": "250", "trace_level": ["TIMESTAMPS", "TENSORS"]}
        )
        assert list(updated.settings["trace_level"].value) == [
            "TIMESTAMPS",
            "TENSORS",
        ]
        assert updated.settings["trace_rate"].value[0] == "250"

    def test_log_settings(self, client):
        updated = client.update_log_settings({"log_verbose_level": 3})
        assert updated.settings["log_verbose_level"].uint32_param == 3
        got = client.get_log_settings(as_json=True)
        assert got["settings"]["log_verbose_level"]["uint32_param"] == 3

    def test_cuda_shm_rejected(self, client):
        with pytest.raises(InferenceServerException, match="CUDA"):
            client.register_cuda_shared_memory("r", b"\x00" * 8, 0, 64)


class TestTenantPropagation:
    """The tenant= constructor kwarg stamps x-tenant-id metadata on every
    verb — unary, async futures, and streams."""

    def test_tenant_kwarg_stamps_unary_and_stream(self):
        from client_tpu.serve.frontdoor import TenantQoS

        qos = TenantQoS()
        with Server(grpc_port=0, qos=qos) as server:
            with grpcclient.InferenceServerClient(
                server.grpc_address, tenant="acme"
            ) as client:
                assert client.is_server_ready()
                inputs, i0, i1 = _simple_inputs()
                result = client.infer("simple", inputs)
                np.testing.assert_array_equal(
                    result.as_numpy("OUTPUT0"), i0 + i1
                )
            snapshot = qos.snapshot()
            assert "acme" in snapshot
            assert snapshot["acme"]["requests"] >= 1

    def test_explicit_header_wins_over_tenant_kwarg(self):
        from client_tpu.serve.frontdoor import TenantQoS

        qos = TenantQoS()
        with Server(grpc_port=0, qos=qos) as server:
            with grpcclient.InferenceServerClient(
                server.grpc_address, tenant="acme"
            ) as client:
                inputs, _, _ = _simple_inputs()
                client.infer(
                    "simple", inputs, headers={"x-tenant-id": "override"}
                )
            snapshot = qos.snapshot()
            assert "override" in snapshot and "acme" not in snapshot

    def test_aio_tenant_kwarg(self):
        import asyncio

        import client_tpu.grpc.aio as aiogrpc
        from client_tpu.serve.frontdoor import TenantQoS

        qos = TenantQoS()
        with Server(grpc_port=0, qos=qos) as server:

            async def run():
                async with aiogrpc.InferenceServerClient(
                    server.grpc_address, tenant="aio-acme"
                ) as client:
                    inputs, _, _ = _simple_inputs()
                    await client.infer("simple", inputs)

            asyncio.run(run())
            assert "aio-acme" in qos.snapshot()
