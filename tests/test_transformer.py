"""Transformer LM: forward/decode equivalence, sharded training step."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from client_tpu.parallel import (
    batch_spec,
    make_mesh,
    named_shardings,
    param_specs,
)
from client_tpu.serve.models import transformer as tfm

CFG = tfm.TransformerConfig(
    vocab_size=128,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
    max_seq=32,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def test_forward_shape_and_finite(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    logits = tfm.forward(params, tokens, CFG)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_prefill_decode_matches_forward(params):
    """Incremental decoding must reproduce the full-sequence logits."""
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0, CFG.vocab_size)
    full = np.asarray(tfm.forward(params, tokens, CFG))

    cache = tfm.init_cache(CFG, 1)
    prefix = tokens[:, :8]
    logits, cache = tfm.prefill(params, prefix, CFG, cache)
    np.testing.assert_allclose(np.asarray(logits), full[:, 7], atol=2e-4, rtol=1e-3)
    for i in range(8, 12):
        logits, cache = tfm.decode_step(params, tokens[:, i], CFG, cache)
        np.testing.assert_allclose(
            np.asarray(logits), full[:, i], atol=2e-4, rtol=1e-3
        )


def test_ring_forward_matches_plain(params):
    mesh = make_mesh(dp=2, tp=2, sp=2)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, CFG.vocab_size)
    plain = np.asarray(tfm.forward(params, tokens, CFG))
    sharded_params = jax.device_put(params, named_shardings(mesh, param_specs(CFG)))
    sharded_tokens = jax.device_put(
        tokens, jax.sharding.NamedSharding(mesh, batch_spec())
    )
    ring = np.asarray(
        tfm.forward(sharded_params, sharded_tokens, CFG, mesh=mesh, attn_impl="ring")
    )
    np.testing.assert_allclose(ring, plain, atol=1e-4, rtol=1e-3)


def test_train_step_reduces_loss(params):
    opt, step = tfm.make_train_step(CFG, learning_rate=1e-2)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 17), 0, CFG.vocab_size)
    p = jax.tree.map(jnp.copy, params)  # step donates its inputs
    first = None
    for _ in range(5):
        p, opt_state, loss = step(p, opt_state, tokens)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_sharded_train_step_runs():
    """dp/tp/sp train step on the 8-device mesh — the dryrun_multichip path."""
    mesh = make_mesh(dp=2, tp=2, sp=2)
    params = tfm.init_params(jax.random.PRNGKey(5), CFG)
    opt, step = tfm.make_train_step(CFG, mesh=mesh, attn_impl="ring")
    shardings = named_shardings(mesh, param_specs(CFG))
    params = jax.device_put(params, shardings)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 17), 0, CFG.vocab_size)
    # seq len 17: forward sees 16 tokens (sp-divisible), targets get 16
    tokens = jax.device_put(
        tokens, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("dp", None))
    )
    params, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))


def test_generate_streams_tokens(params):
    toks = list(
        tfm.generate(params, CFG, prompt=[1, 2, 3], max_new_tokens=4)
    )
    assert len(toks) == 4
    assert all(0 <= t < CFG.vocab_size for t in toks)


def test_generate_pipelined_matches_serial(params):
    """Deferring the D2H readback must not change the token stream."""
    serial = list(
        tfm.generate(params, CFG, prompt=[5, 9], max_new_tokens=12,
                     readback_depth=0)
    )
    for depth in (1, 4, 32):
        pipelined = list(
            tfm.generate(params, CFG, prompt=[5, 9], max_new_tokens=12,
                         readback_depth=depth)
        )
        assert pipelined == serial


def test_generate_pipelined_matches_serial_sampled(params):
    """Sampling path: the key-split schedule is per-step, so the stream is
    depth-invariant there too."""
    kw = dict(prompt=[3, 4, 5], max_new_tokens=10, temperature=0.7,
              key=jax.random.PRNGKey(7))
    serial = list(tfm.generate(params, CFG, readback_depth=0, **kw))
    pipelined = list(tfm.generate(params, CFG, readback_depth=8, **kw))
    assert pipelined == serial
