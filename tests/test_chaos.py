"""Chaos-matrix acceptance: fault-domain hardening end to end.

Three layers under test:

1. the harness itself (``client_tpu/testing/chaos.py``): seeded
   deterministic schedules, the exactly-once step ledger, fault
   dispatch, driver error/wedge collection;
2. replicated sequence state at the engine level: durable
   ``SequenceContext`` snapshots push to peers at each applied step,
   a survivor resumes them (stale rejected, duplicate steps replayed
   idempotently, step gaps rejected);
3. the two fleet acceptances as one-scenario matrix entries:
   - **SIGKILL with active durable sequences** — three real HTTP servers
     behind chaos proxies, a sticky ``ReplicatedClient`` driving durable
     sequences, replica 0 SIGKILLed mid-sequence: every sequence resumes
     byte-exact on a survivor, zero client-visible errors, no
     ``(sequence, step)`` applied twice (orphaned applies on the corpse
     excepted);
   - **anti-entropy convergence** — hot prefix chains proactively pushed
     to peers survive replica 0's SIGKILL: the dead replica's chains are
     retrievable from survivors and save prefill there, byte-exact.

``make soak`` repeats the slow-marked scaled variants.
"""

import json
import os
import threading
import time
import types

import numpy as np
import pytest

import jax

from client_tpu import traceview
from client_tpu.balance.replicated import ReplicatedClient
from client_tpu.serve import InferenceEngine, Model, Server, TensorSpec
from client_tpu.serve.fleet import FleetTier
from client_tpu.serve.flight import FlightRecorder
from client_tpu.tracing import ClientTracer
from client_tpu.serve.lm import LmEngine
from client_tpu.serve.metrics import Registry
from client_tpu.serve.models import transformer as tfm
from client_tpu.testing.chaos import (
    ChaosMatrix,
    ChaosScenario,
    FaultSpec,
    StepLedger,
    assert_byte_exact,
    assert_kv_clean,
    dispatch_fault,
    run_scenario,
)
from client_tpu.testing.faults import FaultProxy

CLOSE = LmEngine.CLOSE


def _tier(**kwargs):
    kwargs.setdefault("gossip_interval_s", 0)
    return FleetTier(**kwargs).start()


def _peer_up(tiers):
    for tier in tiers:
        tier.set_peers([t.address for t in tiers if t is not tier])


def _seq_model(ledger, replica, name="chaos_sequence", busy_s=0.0):
    """Stateful accumulator that records every APPLIED step into the
    ledger — idempotent replays served from the retained rendering never
    reach this function, which is exactly what the exactly-once checker
    verifies.  ``busy_s`` holds the request in-flight so load is visible
    as engine pressure (the autoscale ramp's scale signal)."""

    def fn(inputs, params, ctx):
        value = inputs["INPUT"]
        if busy_s:
            time.sleep(busy_s)
        if ctx is None:
            return {"OUTPUT": value}
        if params.get("sequence_start") or "acc" not in ctx.state:
            ctx.state["acc"] = np.zeros_like(value)
        ctx.state["acc"] = ctx.state["acc"] + value
        ledger.record(ctx.sequence_id, ctx.step + 1, replica)
        return {"OUTPUT": ctx.state["acc"].copy()}

    return Model(
        name,
        inputs=[TensorSpec("INPUT", "INT32", [1])],
        outputs=[TensorSpec("OUTPUT", "INT32", [1])],
        fn=fn,
        stateful=True,
    )


def _seq_request(value, sid, step, start=False, end=False, durable=True):
    return {
        "id": f"s{sid}-{step}",
        "inputs": [{
            "name": "INPUT", "shape": [1], "datatype": "INT32",
            "data": [int(value)],
        }],
        "parameters": {
            "sequence_id": sid,
            "sequence_start": bool(start),
            "sequence_end": bool(end),
            "sequence_durable": bool(durable),
            "sequence_step": int(step),
        },
    }


def _out_value(response):
    return int(response["outputs"][0]["data"][0])


# -- harness units ----------------------------------------------------------

def test_scenario_schedule_is_seed_deterministic():
    faults = [
        FaultSpec("kill_replica", at_s=("uniform", 0.1, 0.9), target=0),
        FaultSpec("refuse", at_s=0.05, target=1),
    ]
    a = ChaosScenario("s", faults, seed=42).schedule()
    b = ChaosScenario("s", faults, seed=42).schedule()
    c = ChaosScenario("s", faults, seed=43).schedule()
    assert [t for t, _ in a] == [t for t, _ in b]  # same seed, same times
    assert [t for t, _ in a] != [t for t, _ in c]  # different seed differs
    assert a[0][1].kind == "refuse"  # sorted by time
    assert 0.1 <= a[1][0] <= 0.9
    with pytest.raises(ValueError):
        ChaosScenario(
            "bad", [FaultSpec("refuse", at_s=("gauss", 0, 1))]
        ).schedule()


def test_step_ledger_exactly_once_semantics():
    ledger = StepLedger()
    ledger.record(1, 1, "r0")
    ledger.record(1, 2, "r0")
    ledger.record(1, 3, "r0")   # applied on r0 but unacked: r0 dies
    ledger.record(1, 3, "r1")   # survivor re-applies from the snapshot
    ledger.record(1, 4, "r1")
    ledger.assert_exactly_once(orphans={"r0"})  # the resume carve-out
    with pytest.raises(AssertionError):
        ledger.assert_exactly_once()  # without the orphan: a duplicate
    assert ledger.steps_for(1) == [1, 2, 3, 4]
    # duplicates on one replica always fail, orphaned or not
    dup = StepLedger()
    dup.record(7, 1, "r0")
    dup.record(7, 1, "r0")
    with pytest.raises(AssertionError):
        dup.assert_exactly_once(orphans={"r0"})
    # a re-apply whose predecessor ran on a SURVIVOR always fails
    forked = StepLedger()
    forked.record(9, 2, "r1")
    forked.record(9, 2, "r2")
    with pytest.raises(AssertionError):
        forked.assert_exactly_once(orphans={"r0"})


def test_run_scenario_collects_errors_and_wedges():
    gate = threading.Event()

    def ok():
        gate.wait(timeout=10)

    def boom():
        raise RuntimeError("driver died")

    scenario = ChaosScenario(
        "units", [FaultSpec("custom", at_s=0.0, fn=gate.set)]
    )
    result = run_scenario(scenario, lambda f: dispatch_fault(f), [ok, boom])
    assert result.wedged == 0
    assert len(result.errors) == 1 and result.errors[0][0] == 1
    with pytest.raises(AssertionError):
        result.assert_clean()
    # a driver that outlives the join timeout is reported wedged
    slow = threading.Event()
    try:
        result = run_scenario(
            ChaosScenario("wedge"), lambda f: None,
            [lambda: slow.wait(timeout=5)], join_timeout_s=0.1,
        )
        assert result.wedged == 1
    finally:
        slow.set()


def test_chaos_matrix_round_under_race_witness(tmp_path):
    """A chaos-matrix round with the dynamic race witness armed (the
    TPULINT_RACE_WITNESS=1 shape `make chaos` runs): concurrent drivers
    hammering the @witness_shared StepLedger stay green through the
    assert_race_witness_clean invariant, and a seeded unguarded-write
    fixture goes red — with the violation evidence dumped to the
    fixture's flight recorder."""
    from client_tpu.analysis.witness import RaceViolation, RaceWitness
    from client_tpu.testing.chaos import assert_race_witness_clean

    class _LedgerFixture:
        def __init__(self, racy):
            self.racy = racy
            self.ledger = StepLedger()  # @witness_shared("_lock")
            self.flight = FlightRecorder(
                dump_dir=str(tmp_path), name="race-round"
            )
            self.seq = 0

        def flight_recorders(self):
            return [self.flight]

        def apply_fault(self, fault):
            dispatch_fault(fault)

        def drivers(self):
            def drive(replica):
                def run():
                    for step in range(40):
                        self.ledger.record(replica, step, f"r{replica}")
                        if self.racy:
                            try:
                                # a deliberately unguarded shared write —
                                # SWALLOWED here so only the matrix
                                # invariant can fail the round
                                self.seq = self.seq + 1
                            except RaceViolation:
                                pass
                return run

            return [drive(0), drive(1), drive(2)]

        def check(self, result):
            result.assert_clean()
            self.ledger.assert_exactly_once()

        def close(self):
            pass

    scenario = ChaosScenario("race-witness-round")

    witness = RaceWitness()
    with witness.installed():
        ChaosMatrix(
            [scenario],
            invariants=[lambda fx, res: assert_race_witness_clean(witness)],
        ).run(lambda s: _LedgerFixture(racy=False))
    assert witness.assert_race_free() > 0  # the ledger WAS witnessed
    assert witness.assert_acyclic() >= 0   # lock-order duty intact

    seeded = RaceWitness()
    seeded.watch_class(_LedgerFixture, fields=("seq",))
    fixtures = []

    def make_racy(s):
        fixtures.append(_LedgerFixture(racy=True))
        return fixtures[-1]

    with seeded.installed():
        with pytest.raises(RaceViolation):
            ChaosMatrix(
                [scenario],
                invariants=[
                    lambda fx, res: assert_race_witness_clean(seeded)
                ],
            ).run(make_racy)
    assert seeded.race_violations
    # the red round dumped its own postmortem via the matrix hook
    flight = fixtures[0].flight
    kinds = [r["kind"] for r in flight.snapshot()]
    assert "chaos_invariant_failure" in kinds
    assert flight.dumps


def test_chaos_matrix_round_under_resource_witness(tmp_path):
    """A chaos-matrix round with the dynamic resource witness armed (the
    TPULINT_RESOURCE_WITNESS=1 shape `make chaos` runs): drivers cycling
    KV block reservations through alloc/release stay green through the
    assert_no_leaked_resources invariant, and a seeded leak — a
    reservation deliberately never released — goes red with the
    acquisition stack in the report."""
    from client_tpu.analysis.witness import ResourceLeakError, ResourceWitness
    from client_tpu.serve.lm.kv import KvBlockPool
    from client_tpu.testing.chaos import assert_no_leaked_resources

    cfg = tfm.TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=96, dtype="float32",
    )

    class _PoolFixture:
        def __init__(self, leak):
            self.leak = leak
            self.leaked = []
            self.pool = KvBlockPool(cfg, n_blocks=16, block_size=4)
            self.flight = FlightRecorder(
                dump_dir=str(tmp_path), name="resource-round"
            )

        def flight_recorders(self):
            return [self.flight]

        def apply_fault(self, fault):
            dispatch_fault(fault)

        def drivers(self):
            def run():
                for _ in range(20):
                    blocks = self.pool.alloc(2)
                    self.pool.retain(blocks)
                    self.pool.release(blocks)
                    self.pool.release(blocks)
                if self.leak:
                    self.leaked.extend(self.pool.alloc(1))

            return [run]

        def check(self, result):
            result.assert_clean()

        def close(self):
            pass

    scenario = ChaosScenario("resource-witness-round")

    witness = ResourceWitness()
    with witness.installed():
        ChaosMatrix(
            [scenario],
            invariants=[lambda fx, res: assert_no_leaked_resources(witness)],
        ).run(lambda s: _PoolFixture(leak=False))
    assert witness.assert_clean() > 0  # the pool WAS witnessed

    seeded = ResourceWitness()
    fixtures = []

    def make_leaky(s):
        fixtures.append(_PoolFixture(leak=True))
        return fixtures[-1]

    with seeded.installed():
        with pytest.raises(ResourceLeakError) as excinfo:
            ChaosMatrix(
                [scenario],
                invariants=[
                    lambda fx, res: assert_no_leaked_resources(seeded)
                ],
            ).run(make_leaky)
    assert "kv-blocks" in str(excinfo.value)
    assert "acquired at" in str(excinfo.value)
    # drain the seeded leak so an outer session-level audit (the
    # TPULINT_RESOURCE_WITNESS=1 conftest hook `make chaos` arms) stays
    # clean — the leak was the test subject, not a real loss
    for fx in fixtures:
        fx.pool.release(fx.leaked)


def test_dispatch_fault_drives_a_fault_proxy():
    import socket

    upstream = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    upstream.bind(("127.0.0.1", 0))
    upstream.listen(4)
    proxy = FaultProxy("%s:%d" % upstream.getsockname()[:2])
    try:
        host, _, port = proxy.address.rpartition(":")
        dispatch_fault(FaultSpec("refuse", target=0), proxies=[proxy])
        # the refused connection dies at accept: either the RST raises
        # or the FIN half of the hard close races it and reads as EOF
        try:
            data = socket.create_connection(
                (host, int(port)), timeout=2
            ).recv(1)
            assert data == b"", "refused connection served data"
        except OSError:
            pass
        dispatch_fault(FaultSpec("restore", target=0), proxies=[proxy])
        sock = socket.create_connection((host, int(port)), timeout=2)
        sock.close()
        killed = []
        dispatch_fault(
            FaultSpec("kill_replica", target=0), proxies=[proxy],
            kill=killed.append,
        )
        assert killed == [0]  # sigkill + the kill hook both fired
        with pytest.raises(ValueError):
            dispatch_fault(FaultSpec("martian"), proxies=[proxy])
    finally:
        proxy.close()
        upstream.close()


# -- replicated sequence state at the engine level --------------------------

def test_durable_sequence_resumes_on_survivor_engine():
    """The tentpole's core path without HTTP in the way: durable steps
    applied on engine A replicate to B's tier; after A's death B resumes
    the sequence byte-exact, replays the duplicate step idempotently,
    and rejects a step gap with a restartable 409."""
    ledger = StepLedger()
    tier_a, tier_b = _tier(replicate_k=1), _tier(replicate_k=1)
    _peer_up([tier_a, tier_b])
    eng_a = InferenceEngine(
        models=[_seq_model(ledger, "rA")], fleet=tier_a
    )
    eng_b = InferenceEngine(
        models=[_seq_model(ledger, "rB")], fleet=tier_b
    )
    try:
        sid, total = 31, 0
        for step, value in enumerate((3, 1, 4), start=1):
            total += value
            response, _ = eng_a.execute(
                "chaos_sequence", "",
                _seq_request(value, sid, step, start=(step == 1)), b"",
            )
            assert _out_value(response) == total
        # each applied step pushed a snapshot before responding
        assert tier_a.stats()["seq_pushes"] == 3
        snap = tier_b.seq_store.get(sid)
        assert snap is not None and snap["step"] == 3
        # A dies unplanned (no drain): B sees step 4 for a sequence it
        # never met, recovers the snapshot from its tier, and continues
        tier_a.close()
        response, _ = eng_b.execute(
            "chaos_sequence", "", _seq_request(5, sid, 4), b"",
        )
        assert _out_value(response) == total + 5
        assert eng_b.metrics.get("ctpu_fleet_seq_resumes_total") == 1
        # the duplicate declared step replays from the retained
        # rendering — same bytes, NO second apply in the ledger
        replay, _ = eng_b.execute(
            "chaos_sequence", "", _seq_request(5, sid, 4), b"",
        )
        assert _out_value(replay) == total + 5
        ledger.assert_exactly_once()
        assert ledger.steps_for(sid) == [1, 2, 3, 4]
        # a declared step AHEAD of the counter is the lost-steps fork:
        # restartable 409, never a silent wrong-state apply
        from client_tpu.utils import InferenceServerException

        with pytest.raises(InferenceServerException) as exc:
            eng_b.execute(
                "chaos_sequence", "", _seq_request(9, sid, 7), b"",
            )
        assert exc.value.status() == "409"
    finally:
        eng_a.close()
        eng_b.close()
        tier_a.close()
        tier_b.close()


def test_sequence_snapshots_reject_stale_and_fork_failed_lookup():
    """Staleness + miss behavior: an older snapshot never overwrites a
    newer one, and with no tier hit a mid-sequence miss falls back to a
    fresh context (today's non-durable semantics, preserved)."""
    ledger = StepLedger()
    tier = _tier()
    engine = InferenceEngine(models=[_seq_model(ledger, "r")], fleet=tier)
    try:
        engine.execute(
            "chaos_sequence", "", _seq_request(2, 5, 1, start=True), b"",
        )
        engine.execute("chaos_sequence", "", _seq_request(3, 5, 2), b"")
        newer = engine.export_sequence(5)
        assert newer["step"] == 2
        older = dict(newer)
        older["step"] = 1
        assert tier.seq_store.put(newer) is True
        assert tier.seq_store.put(older) is False  # stale rejected
        assert tier.seq_store.get(5)["step"] == 2
        assert tier.stats()["seq_stale_rejected"] == 1
        # unknown sequence, tier miss: fresh context (state forks only
        # when there is genuinely nothing to recover)
        response, _ = engine.execute(
            "chaos_sequence", "",
            _seq_request(7, 404, 1, durable=False), b"",
        )
        assert _out_value(response) == 7
    finally:
        engine.close()
        tier.close()


def test_restarted_sequence_epoch_beats_stale_incarnation():
    """A restarted sequence id is a NEW incarnation: its fresh epoch
    must overwrite the dead incarnation's higher-step snapshots on
    peers — and a reachable peer that REJECTS a snapshot as stale must
    not count as a durability ack."""
    ledger = StepLedger()
    tier_a, tier_b = _tier(replicate_k=1), _tier(replicate_k=1)
    _peer_up([tier_a, tier_b])
    eng_a = InferenceEngine(models=[_seq_model(ledger, "rA")],
                            fleet=tier_a)
    eng_b = InferenceEngine(models=[_seq_model(ledger, "rB")],
                            fleet=tier_b)
    try:
        sid = 77
        for step in range(1, 4):
            eng_a.execute(
                "chaos_sequence", "",
                _seq_request(step, sid, step, start=(step == 1)), b"",
            )
        old = tier_b.seq_store.get(sid)
        assert old is not None and old["step"] == 3
        # the client restarts the id (the 409 contract) on replica B:
        # fresh incarnation, step 1 — its snapshot must REPLACE the old
        # incarnation's step-3 leftovers wherever they live
        response, _ = eng_b.execute(
            "chaos_sequence", "", _seq_request(9, sid, 1, start=True), b"",
        )
        assert _out_value(response) == 9
        fresh = eng_b.export_sequence(sid)
        assert fresh["epoch"] > old["epoch"]
        assert tier_b.seq_store.put(dict(fresh)) is True  # overwrites
        stored = tier_b.seq_store.get(sid)
        assert stored["step"] == 1 and stored["epoch"] == fresh["epoch"]
        # the OLD incarnation arriving late (gossip race) is now stale
        assert tier_b.seq_store.put(dict(old)) is False
        # a peer that rejects as stale is NOT a durability ack
        assert tier_a.publish_sequence(dict(old)) == 0
        # and a resume restores the NEW incarnation, not the corpse's
        restored = tier_b.seq_store.get(sid)
        assert restored["epoch"] == fresh["epoch"]
    finally:
        eng_a.close()
        eng_b.close()
        tier_a.close()
        tier_b.close()


def test_drain_exports_sequences_to_the_tier():
    """Planned retire: every live sequence's snapshot lands on a peer
    even when it was never marked durable — drain is free durability."""
    ledger = StepLedger()
    tier_a, tier_b = _tier(), _tier()
    _peer_up([tier_a, tier_b])
    engine = InferenceEngine(models=[_seq_model(ledger, "rA")],
                             fleet=tier_a)
    try:
        engine.execute(
            "chaos_sequence", "",
            _seq_request(4, 11, 1, start=True, durable=False), b"",
        )
        assert tier_b.seq_store.get(11) is None  # not durable: no push yet
        assert engine.drain(timeout_s=5) is True
        snap = tier_b.seq_store.get(11)
        assert snap is not None and snap["step"] == 1
    finally:
        engine.close()
        tier_a.close()
        tier_b.close()


# -- quorum-durable sequences ------------------------------------------------

def test_seq_quorum_arithmetic():
    """ceil((K+1)/2) peers must report ``stored`` before a
    quorum="majority" durable step acks; best-effort mode never requires
    any; an unknown discipline is a loud constructor error."""
    with pytest.raises(ValueError):
        FleetTier(quorum="all")
    tier = _tier()
    try:
        assert tier.quorum == "any"
        assert tier.seq_quorum_required() == 0
    finally:
        tier.close()
    for k, need in ((1, 1), (2, 2), (3, 2), (4, 3), (5, 3)):
        tier = _tier(replicate_k=k, quorum="majority")
        try:
            assert tier.seq_quorum_required() == need, (k, need)
        finally:
            tier.close()


def test_quorum_refusal_is_retryable_and_never_reapplies():
    """Quorum unreachable: the step REFUSES with a retryable 503 naming
    the deficit, stays applied locally exactly once, and the client's
    retry of the SAME declared step (without re-declaring start) acks
    200 as soon as a peer is reachable — through the retained-rendering
    replay, never a second apply."""
    from client_tpu.utils import InferenceServerException

    ledger = StepLedger()
    tier_a = _tier(replicate_k=1, quorum="majority")
    tier_b = _tier(replicate_k=1)
    eng_a = InferenceEngine(models=[_seq_model(ledger, "rA")], fleet=tier_a)
    try:
        # no peers wired: zero acks possible — the partitioned shape
        with pytest.raises(InferenceServerException) as exc:
            eng_a.execute(
                "chaos_sequence", "",
                _seq_request(3, 21, 1, start=True), b"",
            )
        assert exc.value.status() == "503"
        msg = str(exc.value)
        assert "quorum" in msg and "0/1" in msg
        assert ledger.steps_for(21) == [1]  # applied locally, not lost
        assert tier_a.stats()["seq_quorum_refusals"] == 1
        # the partition heals; the retry declares the SAME step and goes
        # through the replay path, which re-publishes before releasing
        # the retained rendering
        tier_a.set_peers([tier_b.address])
        response, _ = eng_a.execute(
            "chaos_sequence", "", _seq_request(3, 21, 1), b"",
        )
        assert _out_value(response) == 3
        assert ledger.steps_for(21) == [1]  # STILL exactly once
        ledger.assert_exactly_once()
        assert tier_a.stats()["seq_quorum_acks"] >= 1
        snap = tier_b.seq_store.get(21)
        assert snap is not None and snap["step"] == 1
        # and the sequence continues normally, quorum-durable per step
        response, _ = eng_a.execute(
            "chaos_sequence", "", _seq_request(4, 21, 2), b"",
        )
        assert _out_value(response) == 7
        assert tier_b.seq_store.get(21)["step"] == 2
    finally:
        eng_a.close()
        tier_a.close()
        tier_b.close()


def test_stale_peer_reply_is_not_a_quorum_ack():
    """A reachable peer that REJECTS the snapshot as stale answered the
    RPC but stored nothing — it must not count toward the write quorum
    (the ACK-BEFORE-STORE lint rule guards this exact shape)."""
    from client_tpu.utils import InferenceServerException

    ledger = StepLedger()
    tier_a = _tier(replicate_k=1, quorum="majority")
    tier_b = _tier(replicate_k=1)
    _peer_up([tier_a, tier_b])
    eng_a = InferenceEngine(models=[_seq_model(ledger, "rA")], fleet=tier_a)
    eng_b = InferenceEngine(models=[_seq_model(ledger, "rB")], fleet=tier_b)
    try:
        # poison B's store with a higher-epoch incarnation of the id so
        # A's pushes are stale-rejected despite B being fully reachable
        eng_b.execute(
            "chaos_sequence", "",
            _seq_request(1, 55, 1, start=True, durable=False), b"",
        )
        poisoned = eng_b.export_sequence(55)
        poisoned["epoch"] = float(poisoned["epoch"]) + 1e6
        assert tier_b.seq_store.put(dict(poisoned)) is True
        with pytest.raises(InferenceServerException) as exc:
            eng_a.execute(
                "chaos_sequence", "",
                _seq_request(5, 55, 1, start=True), b"",
            )
        assert exc.value.status() == "503"
        msg = str(exc.value)
        assert "0/1" in msg  # the reply arrived but was NOT an ack
        assert "open breakers: none" in msg  # transport was healthy
        assert tier_b.stats()["seq_stale_rejected"] >= 1
        assert tier_a.stats()["seq_quorum_refusals"] >= 1
    finally:
        eng_a.close()
        eng_b.close()
        tier_a.close()
        tier_b.close()


def test_dispatch_partition_and_heal_fleet_tiers():
    """The partition fault kind: tiers in different groups cannot
    exchange frames (both directions), same-group tiers still can, an
    address OUTSIDE the partitioned set is unaffected, and heal restores
    everything."""
    tiers = [_tier() for _ in range(3)]
    _peer_up(tiers)
    outside = _tier()
    try:
        dispatch_fault(
            FaultSpec("partition", groups=[[0], [1, 2]]), tiers=tiers
        )
        with pytest.raises(OSError, match="partitioned"):
            tiers[0]._peer_call(tiers[1].address, {"op": "ping"})
        with pytest.raises(OSError, match="partitioned"):
            tiers[1]._peer_call(tiers[0].address, {"op": "ping"})
        tiers[1]._peer_call(tiers[2].address, {"op": "ping"})  # same group
        tiers[0]._peer_call(outside.address, {"op": "ping"})   # unlisted
        dispatch_fault(FaultSpec("heal"), tiers=tiers)
        tiers[0]._peer_call(tiers[1].address, {"op": "ping"})
    finally:
        for tier in tiers:
            tier.close()
        outside.close()


def test_best_effort_acks_without_quorum_and_loss_is_visible():
    """The quorum="any" contrast: under a partition, durable steps still
    ack 200 with ZERO peer acks (local-only durability), so the
    replica's death CAN lose them — but the loss surfaces as a loud
    restartable 409 on the survivor, never a silent wrong answer."""
    from client_tpu.testing.chaos import heal_fleet, partition_fleet
    from client_tpu.utils import InferenceServerException

    ledger = StepLedger()
    tier_a = _tier(replicate_k=1)  # quorum="any" is the default
    tier_b = _tier(replicate_k=1)
    _peer_up([tier_a, tier_b])
    partition_fleet([tier_a, tier_b], groups=[[0], [1]])
    eng_a = InferenceEngine(models=[_seq_model(ledger, "rA")], fleet=tier_a)
    eng_b = InferenceEngine(models=[_seq_model(ledger, "rB")], fleet=tier_b)
    try:
        total = 0
        for step, value in enumerate((2, 4), start=1):
            total += value
            response, _ = eng_a.execute(
                "chaos_sequence", "",
                _seq_request(value, 61, step, start=(step == 1)), b"",
            )
            assert _out_value(response) == total  # acked best-effort
        stats = tier_a.stats()
        assert stats["seq_quorum_acks"] == 0  # no quorum accounting
        assert stats["seq_quorum_refusals"] == 0
        assert tier_b.seq_store.get(61) is None  # nothing replicated
        # A dies unplanned; its acked-but-unreplicated steps are gone —
        # the survivor refuses with the restartable 409 rather than
        # serving silently forked state
        tier_a.close()
        eng_a.close()
        heal_fleet([tier_b])
        with pytest.raises(InferenceServerException) as exc:
            eng_b.execute(
                "chaos_sequence", "", _seq_request(9, 61, 3), b"",
            )
        assert exc.value.status() == "409"
    finally:
        eng_a.close()
        eng_b.close()
        tier_a.close()
        tier_b.close()


# -- acceptance 1: three-replica SIGKILL with active durable sequences ------

class _SeqChaosFixture:
    """Three HTTP servers behind chaos proxies, one sticky replicated
    client, N durable sequences as drivers.  ``check`` asserts the
    scenario's cross-cutting invariants."""

    MODEL = "chaos_sequence"

    def __init__(self, scenario):
        self.scenario = scenario
        self.ledger = StepLedger()
        self.sessions = int(scenario.params.get("sessions", 6))
        self.steps = int(scenario.params.get("steps", 8))
        self.think_s = float(scenario.params.get("think_s", 0.04))
        rng = scenario.rng()
        self.values = [
            [rng.randrange(1, 9) for _ in range(self.steps)]
            for _ in range(self.sessions)
        ]
        self.delivered = [[] for _ in range(self.sessions)]
        self.tiers = [
            _tier(replicate_k=1, fan_out=2, lookup_timeout_s=0.5)
            for _ in range(3)
        ]
        _peer_up(self.tiers)
        # fleet-wide tracing (the one-trace failover acceptance): each
        # replica writes its own trace file, the client a fourth —
        # traceview joins them by trace id after the run
        self.trace_dir = scenario.params.get("trace_dir")
        self.trace_files = []
        self.servers = []
        self.proxies = []
        for i, tier in enumerate(self.tiers):
            server = Server(
                models=[_seq_model(self.ledger, f"r{i}")],
                with_default_models=False, fleet=tier,
            ).start()
            if self.trace_dir:
                trace_file = os.path.join(
                    self.trace_dir, f"replica{i}.jsonl"
                )
                self.trace_files.append(trace_file)
                server.engine.update_trace_settings({
                    "trace_level": ["TIMESTAMPS"], "trace_rate": "1",
                    "trace_count": "-1", "trace_file": trace_file,
                })
            self.servers.append(server)
            self.proxies.append(FaultProxy(server.http_address))
        tracer = None
        if self.trace_dir:
            client_file = os.path.join(self.trace_dir, "client.jsonl")
            self.trace_files.append(client_file)
            tracer = ClientTracer(trace_file=client_file, trace_rate=1)
        self.client = ReplicatedClient(
            [proxy.address for proxy in self.proxies],
            transport="http", policy="sticky", probe_interval_s=0.5,
            tracer=tracer,
        )

    def apply_fault(self, fault):
        dispatch_fault(fault, proxies=self.proxies, kill=self._kill)

    def _kill(self, target):
        # SIGKILL semantics: connections RST, listener refused (the
        # proxy's sigkill already ran), and the server stops WITHOUT
        # drain — its sequence state and caches die with it.  Only the
        # snapshots it pushed at each applied step survive.
        self.servers[target].stop()

    def drivers(self):
        from client_tpu.http import InferInput

        def driver(index):
            sid = 1000 + index
            expected = 0
            for step in range(1, self.steps + 1):
                value = self.values[index][step - 1]
                expected += value
                inp = InferInput("INPUT", [1], "INT32")
                inp.set_data_from_numpy(np.array([value], np.int32))
                result = self.client.infer(
                    self.MODEL, [inp],
                    sequence_id=sid,
                    sequence_start=(step == 1),
                    sequence_end=(step == self.steps),
                    sequence_durable=True,
                    sequence_step=step,
                )
                got = int(result.as_numpy("OUTPUT")[0])
                assert got == expected, (
                    f"sequence {sid} step {step}: got {got}, "
                    f"want {expected} — resumed state diverged"
                )
                self.delivered[index].append(got)
                time.sleep(self.think_s)

        return [
            (lambda i=i: driver(i)) for i in range(self.sessions)
        ]

    def check(self, result):
        result.assert_clean()  # zero client-visible errors, no wedges
        # byte-exact: every session saw the exact running-sum series
        for index in range(self.sessions):
            want = list(np.cumsum(self.values[index]))
            assert_byte_exact(
                self.delivered[index], want, label=f"sequence {1000 + index}"
            )
        # exactly-once: no (sequence, step) applied twice — applies
        # orphaned on the SIGKILLed replica (applied but never acked /
        # never replicated) are superseded by the survivor's resume
        self.ledger.assert_exactly_once(orphans={"r0"})
        for index in range(self.sessions):
            assert self.ledger.steps_for(1000 + index) == list(
                range(1, self.steps + 1)
            )
        # the kill actually hit live state: replica 0 had applied steps,
        # and every sequence that CROSSED the kill (applies on r0 AND on
        # a survivor) resumed from a replicated snapshot — a fork to
        # fresh state would already have failed the byte-exact check,
        # and a crossing with zero resumes means the tier never served
        replicas = {r for _s, _p, r, _t in self.ledger.applies()}
        assert "r0" in replicas, "replica 0 never served — kill proved nothing"
        crossed = {
            sid
            for sid, _step, replica, _t in self.ledger.applies()
            if replica == "r0"
        } & {
            sid
            for sid, _step, replica, _t in self.ledger.applies()
            if replica != "r0"
        }
        resumes = sum(
            server.engine.metrics.get("ctpu_fleet_seq_resumes_total") or 0
            for server in self.servers[1:]
        )
        if self.scenario.params.get("require_resume"):
            # the deterministic acceptance pins its timing so sequences
            # MUST straddle the kill; randomized-timing soak scenarios
            # may legitimately kill after r0's sequences completed
            assert crossed, "no sequence straddled the kill"
        if crossed:
            assert resumes > 0, (
                f"{len(crossed)} sequence(s) crossed the kill but none "
                "resumed from a replicated snapshot"
            )
        pushes = sum(t.stats()["seq_pushes"] for t in self.tiers)
        assert pushes > 0

    def close(self):
        self.client.close()
        for proxy in self.proxies:
            proxy.close()
        for server in self.servers[1:]:
            server.stop()
        for tier in self.tiers[1:]:
            tier.close()
        self.tiers[0].close()


def _seq_sigkill_scenario(name, sessions, steps, at_s, seed=7, **extra):
    return ChaosScenario(
        name,
        [FaultSpec("kill_replica", at_s=at_s, target=0)],
        seed=seed, sessions=sessions, steps=steps, **extra,
    )


def test_sigkill_with_active_durable_sequences():
    matrix = ChaosMatrix([
        _seq_sigkill_scenario("seq-sigkill", sessions=5, steps=8,
                              at_s=0.35, think_s=0.08,
                              require_resume=True),
    ])
    results = matrix.run(_SeqChaosFixture, join_timeout_s=180)
    assert results[0].fired, "the kill never fired"


def test_sigkill_failover_joins_one_trace(tmp_path, capsys):
    """Acceptance: a kill-mid-stream failover reads as ONE trace spanning
    three processes' trace files.  The client pins every step of a
    sequence under one trace id, the dead replica's server spans joined
    it via traceparent, the survivor's ``__seq_resume__`` marker
    CONTINUES it from the replicated snapshot, and the peer-tier child
    spans (durability ``seq_put`` pushes, the resume-side lookup) hang
    under it — and traceview joins all four files into one timeline."""
    scenario = _seq_sigkill_scenario(
        "seq-sigkill-traced", sessions=5, steps=8, at_s=0.35,
        think_s=0.08, require_resume=True, trace_dir=str(tmp_path),
    )
    matrix = ChaosMatrix([scenario])
    results = matrix.run(_SeqChaosFixture, join_timeout_s=180)
    assert results[0].fired, "the kill never fired"
    files = sorted(str(p) for p in tmp_path.glob("*.jsonl"))
    assert len(files) == 4  # three replicas + the client
    records = traceview.load_records(files)
    traces = traceview.join_traces(records)
    by_file = {
        f: {r.get("trace_id") for r in traceview.load_records([f])}
        for f in files
    }
    # a survivor resumed the dead replica's sequence INTO the same trace
    resumes = [
        r for r in records if r.get("model_name") == "__seq_resume__"
    ]
    assert resumes, "no resume marker span — the failover left no trace"
    trace_id = resumes[0]["trace_id"]
    spans = traces[trace_id]
    assert {r.get("source") for r in spans} == {"client", "server"}
    # the ONE trace id appears in the client's file and >= 2 replicas'
    holding = [f for f, tids in by_file.items() if trace_id in tids]
    assert any(f.endswith("client.jsonl") for f in holding)
    assert sum(1 for f in holding if "replica" in f) >= 2, (
        f"trace {trace_id} should span the dead replica AND a survivor; "
        f"found only {holding}"
    )
    # peer-tier child spans under the same trace (durability pushes
    # and/or the survivor's sequence lookup)
    assert any(
        str(r.get("model_name", "")).startswith("__peer_seq")
        for r in spans
    )
    # the client's attempt pairs show the endpoint hop across the kill
    endpoints = {
        ts.get("endpoint")
        for r in spans if r.get("source") == "client"
        for ts in r.get("timestamps") or ()
        if ts.get("endpoint")
    }
    assert len(endpoints) >= 2, (
        f"expected attempts on both sides of the kill, saw {endpoints}"
    )
    # the traceview CLI joins the same story (and --format json scripts)
    assert traceview.main(["--format", "json", "--trace", trace_id,
                           *files]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    doc = json.loads(out[0])
    assert doc["trace_id"] == trace_id
    assert doc["critical_path"]["total_ms"] > 0
    assert doc["critical_path"]["peer_ms"] > 0


def test_invariant_failure_dumps_flight_recorders(tmp_path):
    """A failed chaos invariant ships its own postmortem: ChaosMatrix
    dumps every reachable flight recorder before the failure
    propagates, and the dump names the scenario and the error."""
    recorder = FlightRecorder(dump_dir=str(tmp_path), name="r0")
    recorder.note("tick", n=1)

    class _Fixture:
        servers = [types.SimpleNamespace(
            engine=types.SimpleNamespace(flight=recorder)
        )]

        def apply_fault(self, fault):
            pass

        def drivers(self):
            return []

        def check(self, result):
            raise AssertionError("invariant broken")

    matrix = ChaosMatrix([ChaosScenario("boom")])
    with pytest.raises(AssertionError, match="invariant broken"):
        matrix.run(lambda scenario: _Fixture(), join_timeout_s=5)
    dumps = sorted(tmp_path.glob("flight-*.jsonl"))
    assert dumps, "no flight dump written on invariant failure"
    lines = [json.loads(line) for line in open(dumps[0])]
    assert lines[0]["kind"] == "flight_dump"
    assert lines[0]["reason"].startswith("chaos-boom")
    kinds = {r["kind"] for r in lines[1:]}
    assert {"tick", "chaos_invariant_failure"} <= kinds


@pytest.mark.slow
def test_sigkill_durable_sequences_soak():
    """Scaled matrix for `make soak`: more sessions, longer sequences,
    randomized kill timing — repetition over seeds is what finds the
    apply/publish/ack window races."""
    matrix = ChaosMatrix([
        _seq_sigkill_scenario(f"seq-sigkill-{seed}", sessions=8, steps=12,
                              at_s=("uniform", 0.3, 0.9), seed=seed,
                              think_s=0.1)
        for seed in (11, 23)
    ])
    matrix.run(_SeqChaosFixture, join_timeout_s=300)


# -- acceptance 2: anti-entropy convergence under SIGKILL -------------------

CFG = tfm.TransformerConfig(
    vocab_size=128,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
    max_seq=96,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def _serial(params, prompt, n):
    return list(tfm.generate(params, CFG, prompt, n, readback_depth=0))


def _collect(q, timeout=120):
    out = []
    while True:
        tok = q.get(timeout=timeout)
        if tok is CLOSE:
            return out
        out.append(tok)


class _AntiEntropyFixture:
    """Three in-process LM replicas; replica 0 serves a hot shared
    prefix whose chain the anti-entropy loop pushes to peers; replica 0
    is then SIGKILLed and the sessions run on survivors — the chain must
    be retrievable from peers and save prefill there."""

    def __init__(self, scenario, params):
        self.scenario = scenario
        self.params = params
        self.shared = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3]
        self.n_sessions = int(scenario.params.get("sessions", 4))
        self.budget = int(scenario.params.get("budget", 6))
        self.killed = threading.Event()
        self.tiers = [
            _tier(replicate_k=2, hot_hits=2, fan_out=2)
            for _ in range(3)
        ]
        _peer_up(self.tiers)
        self.engines = [
            LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                     block_size=8, prefill_chunk=16, min_bucket=4,
                     registry=Registry(), fleet=tier)
            for tier in self.tiers
        ]
        self.outputs = [None] * self.n_sessions
        # replica 0 serves the shared prefix HOT (re-publishes past the
        # first insert bump the chain's demand counter to hot_hits=2),
        # then the anti-entropy pass pushes the chain to both peers —
        # all BEFORE the kill, which is the entire point: pull-only
        # tiers lose content a dead replica never served to a peer
        for _ in range(3):
            _collect(self.engines[0].submit(self.shared + [99], 2)[0])
        pushed = self.tiers[0].replicate_now()
        assert pushed >= 1, "hot chain never replicated"

    def apply_fault(self, fault):
        dispatch_fault(fault, kill=self._kill)

    def _kill(self, target):
        self.killed.set()
        self.engines[target].close()
        self.tiers[target].close()

    def drivers(self):
        def driver(index):
            prompt = self.shared + [10 + index] * 3
            # spread sessions over the fleet; a session landing on the
            # corpse hops to a survivor (the client-side failover shape)
            order = [
                self.engines[(index + hop) % 3] for hop in range(3)
            ]
            for _attempt in range(6):
                engine = next(
                    e for e in order
                    if not (e is self.engines[0] and self.killed.is_set())
                )
                try:
                    got = _collect(
                        engine.submit(prompt, self.budget)[0]
                    )
                except Exception:
                    continue  # engine closed mid-submit: hop
                if len(got) >= self.budget:
                    self.outputs[index] = got
                    return
            raise AssertionError(f"session {index} never completed")

        return [(lambda i=i: driver(i)) for i in range(self.n_sessions)]

    def check(self, result):
        result.assert_clean()
        # the killed replica's hot chain is retrievable from BOTH peers
        for tier in self.tiers[1:]:
            got = tier.store.lookup(np.asarray(self.shared), 8, 2,
                                    count_hits=False)
            assert got is not None and got[0] == 2, (
                "killed replica's hot chain not on this survivor"
            )
        # byte-exact on survivors
        for index in range(self.n_sessions):
            prompt = self.shared + [10 + index] * 3
            assert_byte_exact(
                self.outputs[index],
                _serial(self.params, prompt, self.budget),
                label=f"session {index}",
            )
        # and the replicated chain actually saved prefill somewhere: a
        # survivor either adopted peer blocks or hit its local trie on
        # the shared prefix
        saved = 0
        for engine in self.engines[1:]:
            saved += engine.fleet_stats()["remote_blocks"]
            saved += engine.prefix_stats().get("hits", 0)
        assert saved > 0, "replicated chain never saved any prefill"

    def close(self):
        for engine in self.engines[1:]:
            engine.close()
        for tier in self.tiers[1:]:
            tier.close()
        for engine in self.engines[1:]:
            assert_kv_clean(engine)


def test_anti_entropy_survives_sigkill(params):
    scenario = ChaosScenario(
        "anti-entropy",
        [FaultSpec("kill_replica", at_s=0.1, target=0)],
        seed=3, sessions=4, budget=6,
    )
    matrix = ChaosMatrix([scenario])
    matrix.run(lambda s: _AntiEntropyFixture(s, params),
               join_timeout_s=300)


@pytest.mark.slow
def test_anti_entropy_sigkill_soak(params):
    scenario = ChaosScenario(
        "anti-entropy-soak",
        [FaultSpec("kill_replica", at_s=("uniform", 0.05, 0.5), target=0)],
        seed=17, sessions=8, budget=10,
    )
    ChaosMatrix([scenario]).run(
        lambda s: _AntiEntropyFixture(s, params), join_timeout_s=600,
    )


# -- speculative decoding under cancel/preempt chaos ------------------------

class _SpecChaosFixture:
    """Spec-enabled engine under cancel + priority-preemption churn with
    drafts in flight.  The verify tick writes k+1 KV positions per pass
    and rejected positions rewind by POINTER (garbage stays inside the
    lane's own reservation, overwritten before it can be attended) — so
    whatever the churn interrupts, retire/preempt releases whole
    reservations and the pool must end fully free.  The preempted lane
    resumes byte-exact with a fresh LaneSpec (drafter state rebuilt from
    the prompt), which is the swap/recompute guarantee extended to the
    draft/verify path."""

    def __init__(self, scenario, params):
        self.scenario = scenario
        self.params = params
        # pool of 12 blocks.  A (12-token prompt + 60 budget) reserves
        # 9; B (+12 budget) reserves 3 — together they fill the pool.
        # B cancelling mid-stream frees its LANE but leaves A holding 9
        # blocks, so the gold admission (needs 7) exhausts the pool and
        # MUST preempt A: preemption is pool-driven in this engine, not
        # lane-driven.
        self.engine = LmEngine(
            params, CFG, max_slots=2, lane_counts=(2,),
            block_size=8, prefill_chunk=16, min_bucket=4,
            pool_tokens=96, speculative={"k": 4},
            tenant_priority={"gold": 10.0}, registry=Registry(),
        )
        self.prompts = {
            "a": [5, 6] * 6,   # periodic: the n-gram drafter fires
            "b": [7, 8] * 6,
            "gold": [9, 7] * 6,
        }
        self.outputs = {}
        self.started_a = threading.Event()

    def apply_fault(self, fault):
        dispatch_fault(fault)

    def drivers(self):
        def stream_a():
            q, _ = self.engine.submit(self.prompts["a"], 60, tenant="free")
            first = q.get(timeout=120)
            assert first is not CLOSE
            self.started_a.set()
            out = [first]
            while True:
                tok = q.get(timeout=120)
                if tok is CLOSE:
                    break
                out.append(tok)
            self.outputs["a"] = out

        def cancel_then_gold():
            self.started_a.wait(timeout=120)
            # B streams a couple of spec-delivered tokens, then cancels
            # with drafts in flight — its lane and blocks must come back
            q, handle = self.engine.submit(
                self.prompts["b"], 12, tenant="free"
            )
            for _ in range(2):
                if q.get(timeout=120) is CLOSE:
                    break
            self.engine.cancel(handle)
            while q.get(timeout=120) is not CLOSE:
                pass
            # now the pool can't fit gold beside A: admission preempts A
            # (possibly mid-verify round — verify never spans a pass
            # boundary, so the swap sees a consistent lane)
            q, _ = self.engine.submit(
                self.prompts["gold"], 40, tenant="gold"
            )
            self.outputs["gold"] = _collect(q)

        return [stream_a, cancel_then_gold]

    def check(self, result):
        result.assert_clean()
        assert self.engine.preempt_stats()["preemptions"] >= 1, (
            "gold admission never preempted the free-tier lane"
        )
        stats = self.engine.spec_stats()
        assert stats["accepted"] > 0, "speculation never engaged"
        # survivors byte-exact: the gold stream throughout, and stream A
        # across its preempt/resume (fresh LaneSpec on swap-in)
        assert_byte_exact(
            self.outputs.get("gold"),
            _serial(self.params, self.prompts["gold"], 40), label="gold",
        )
        assert_byte_exact(
            self.outputs.get("a"),
            _serial(self.params, self.prompts["a"], 60), label="stream a",
        )

    def close(self):
        self.engine.close()
        assert_kv_clean(self.engine)


def test_spec_cancel_preempt_round_never_leaks(params):
    from client_tpu.analysis.witness import ResourceWitness

    scenario = ChaosScenario("spec-cancel-preempt", seed=5)
    witness = ResourceWitness()
    # the leak checkpoint is AFTER the matrix round closes the engine:
    # mid-round the prefix cache legitimately holds retired prompt
    # blocks, so an in-round assert_no_leaked_resources invariant would
    # flag working-as-intended cache retention
    with witness.installed():
        ChaosMatrix([scenario]).run(
            lambda s: _SpecChaosFixture(s, params), join_timeout_s=300
        )
    assert witness.assert_clean() > 0  # KV reservations WERE witnessed


# -- acceptance 3: network partition vs the write quorum --------------------

class _QuorumPartitionFixture:
    """Three engine replicas with majority-quorum durable sequences; a
    network partition isolates replica 0 from both peers mid-run, then
    heals.  Minority-side steps REFUSE (retryable 503, retried by the
    driver) until the heal; majority-side steps keep acking straight
    through the partition.  After the run replica 0 dies WITHOUT drain
    and every minority sequence resumes byte-exact on a survivor —
    possible only because no 200 was ever returned for a step whose
    snapshot had not reached a peer (never acks-then-loses)."""

    MINORITY = 4   # sequences driven on (to-be-partitioned) replica 0
    MAJORITY = 2   # sequences driven on replica 1

    def __init__(self, scenario):
        self.scenario = scenario
        self.ledger = StepLedger()
        self.steps = int(scenario.params.get("steps", 8))
        self.think_s = float(scenario.params.get("think_s", 0.1))
        rng = scenario.rng()
        self.n = self.MINORITY + self.MAJORITY
        self.values = [
            [rng.randrange(1, 9) for _ in range(self.steps)]
            for _ in range(self.n)
        ]
        self.refusals = []
        self.tiers = [
            _tier(replicate_k=1, quorum="majority", fan_out=2,
                  lookup_timeout_s=0.3, failure_threshold=2,
                  reset_timeout_s=0.25)
            for _ in range(3)
        ]
        _peer_up(self.tiers)
        self.engines = [
            InferenceEngine(models=[_seq_model(self.ledger, f"r{i}")],
                            fleet=tier)
            for i, tier in enumerate(self.tiers)
        ]
        self.killed = False

    def apply_fault(self, fault):
        dispatch_fault(fault, tiers=self.tiers)

    def drivers(self):
        from client_tpu.utils import InferenceServerException

        def driver(index):
            sid = 500 + index
            engine = self.engines[0 if index < self.MINORITY else 1]
            expected = 0
            for step in range(1, self.steps + 1):
                value = self.values[index][step - 1]
                expected += value
                start = step == 1
                deadline = time.monotonic() + 60
                while True:
                    try:
                        response, _ = engine.execute(
                            "chaos_sequence", "",
                            _seq_request(value, sid, step, start=start),
                            b"",
                        )
                        break
                    except InferenceServerException as exc:
                        # quorum unreachable: retryable 503.  The retry
                        # declares the SAME step WITHOUT re-declaring
                        # start (the step stayed applied locally; a
                        # restart would fork a fresh incarnation)
                        assert exc.status() == "503", exc
                        assert "quorum" in str(exc)
                        start = False
                        self.refusals.append((sid, step))
                        if time.monotonic() > deadline:
                            raise
                        time.sleep(0.05)
                assert _out_value(response) == expected, (sid, step)
                time.sleep(self.think_s)

        return [(lambda i=i: driver(i)) for i in range(self.n)]

    def check(self, result):
        result.assert_clean()  # every refused step eventually acked
        if self.scenario.params.get("require_refusal", True):
            assert self.refusals, "the partition never refused a step"
        # only the minority side ever refused: the majority side kept
        # its quorum (1 reachable peer) straight through the partition
        refused_sids = {sid for sid, _step in self.refusals}
        assert refused_sids <= {
            500 + i for i in range(self.MINORITY)
        }, f"majority-side sequences refused: {refused_sids}"
        stats = self.tiers[0].stats()
        assert stats["seq_quorum_refusals"] >= len(self.refusals)
        assert stats["seq_quorum_acks"] >= self.MINORITY * self.steps
        # replica 0 dies UNPLANNED (no drain).  Every step it ever acked
        # is on a survivor by the quorum contract — resume each minority
        # sequence there and apply one more step, byte-exact
        self.engines[0].close()
        self.tiers[0].close()
        self.killed = True
        for index in range(self.MINORITY):
            sid = 500 + index
            total = int(np.sum(self.values[index]))
            response, _ = self.engines[1].execute(
                "chaos_sequence", "",
                _seq_request(7, sid, self.steps + 1), b"",
            )
            assert _out_value(response) == total + 7, (
                f"sequence {sid} resumed with lost acked steps"
            )
        # no (sequence, step) applied twice anywhere: refused steps were
        # never re-applied (the replay path re-published instead), and
        # the resumes continued from the replicated snapshots
        self.ledger.assert_exactly_once()

    def close(self):
        for engine in self.engines:
            engine.close()
        for tier in self.tiers:
            tier.close()


def test_partitioned_quorum_never_acks_then_loses():
    scenario = ChaosScenario(
        "quorum-partition",
        [FaultSpec("partition", at_s=0.25, groups=[[0], [1, 2]]),
         FaultSpec("heal", at_s=0.7)],
        seed=13, steps=8, think_s=0.1,
    )
    results = ChaosMatrix([scenario]).run(
        _QuorumPartitionFixture, join_timeout_s=180,
    )
    assert results[0].fired, "the partition never fired"


@pytest.mark.slow
def test_partitioned_quorum_soak():
    """Scaled matrix for `make soak`: randomized partition windows over
    seeds — the refusal/heal/retry races live in the window edges."""
    matrix = ChaosMatrix([
        ChaosScenario(
            f"quorum-partition-{seed}",
            [FaultSpec("partition", at_s=("uniform", 0.1, 0.4),
                       groups=[[0], [1, 2]]),
             FaultSpec("heal", at_s=("uniform", 0.6, 1.1))],
            seed=seed, steps=12, think_s=0.12, require_refusal=False,
        )
        for seed in (5, 29)
    ])
    matrix.run(_QuorumPartitionFixture, join_timeout_s=300)


# -- acceptance 4: diurnal ramp against the elastic fleet -------------------

class _AutoscaleRampFixture:
    """A diurnal load ramp against an elastic fleet: one floor replica,
    an Autoscaler steering real in-process HTTP servers from gossiped
    pressure, a sticky client driving durable sequences.  The burst
    forces scale-up (prefix-aware peer wiring + anti-entropy warm +
    probation ramp before traffic); the quiet tail forces the fleet back
    down THROUGH drain — zero client-visible errors, zero lost
    sequences, and the fleet converges to the floor."""

    MODEL = "chaos_sequence"

    def __init__(self, scenario):
        from client_tpu.balance.pool import EndpointPool
        from client_tpu.serve.autoscale import (
            AutoscalePolicy,
            Autoscaler,
            ServerReplicaLauncher,
        )

        self.scenario = scenario
        self.ledger = StepLedger()
        self.base = int(scenario.params.get("base", 1))
        self.burst = int(scenario.params.get("burst", 6))
        self.tail = int(scenario.params.get("tail", 1))
        self.steps = int(scenario.params.get("steps", 8))
        rng = scenario.rng()
        self.n = self.base + self.burst + self.tail
        self.values = [
            [rng.randrange(1, 9) for _ in range(self.steps)]
            for _ in range(self.n)
        ]
        self.delivered = [[] for _ in range(self.n)]
        self.settled = threading.Event()
        self._lock = threading.Lock()
        self._spawned = 0
        self._load_left = self.n

        def models():
            with self._lock:
                name = f"r{self._spawned}"
                self._spawned += 1
            return [_seq_model(self.ledger, name, busy_s=0.05)]

        self.launcher = ServerReplicaLauncher(
            models,
            fleet_kwargs=dict(gossip_interval_s=0, replicate_k=1,
                              fan_out=2, lookup_timeout_s=0.5),
            drain_timeout_s=30.0,
        )
        floor = self.launcher.spawn()
        self.registry = Registry()
        self.pool = EndpointPool([floor.url])
        self.autoscaler = Autoscaler(
            self.pool, self.launcher,
            policy=AutoscalePolicy(
                min_replicas=1, max_replicas=3, scale_up_at=3.0,
                scale_down_at=1.0, up_after=2, down_after=5,
                cooldown_s=0.8, tick_interval_s=0.1,
            ),
            registry=self.registry,
        ).adopt([floor])
        self.client = ReplicatedClient(
            self.pool, transport="http", policy="sticky",
            probe_interval_s=None,
        )
        assert self.pool.start_probes(self._probe, interval_s=0.15)

    def _probe(self, url):
        """Readiness + gossip in one round trip: the real HTTP health
        verb for state, the replica's fleet peer port for the pressure
        signals the autoscaler steers on."""
        from client_tpu.serve.fleet import fetch_summary
        from client_tpu.utils import SERVER_UNREACHABLE

        handle = next(
            (h for h in self.autoscaler.replicas() if h.url == url), None
        )
        if handle is None:
            return SERVER_UNREACHABLE
        state = self.client.client_for(url).server_state(timeout_s=1.0)
        try:
            summary = fetch_summary(handle.fleet_address, timeout_s=1.0)
        except OSError:
            return state
        return state, summary, summary["pressure"]

    def apply_fault(self, fault):
        dispatch_fault(fault)

    def drivers(self):
        from client_tpu.http import InferInput

        def load(index, delay_s, think_s):
            try:
                sid = 2000 + index
                expected = 0
                time.sleep(delay_s)
                for step in range(1, self.steps + 1):
                    value = self.values[index][step - 1]
                    expected += value
                    inp = InferInput("INPUT", [1], "INT32")
                    inp.set_data_from_numpy(np.array([value], np.int32))
                    result = self.client.infer(
                        self.MODEL, [inp],
                        sequence_id=sid,
                        sequence_start=(step == 1),
                        sequence_end=(step == self.steps),
                        sequence_durable=True,
                        sequence_step=step,
                    )
                    got = int(result.as_numpy("OUTPUT")[0])
                    assert got == expected, (sid, step, got, expected)
                    self.delivered[index].append(got)
                    time.sleep(think_s)
            finally:
                with self._lock:
                    self._load_left -= 1

        def controller():
            # the fixture owns the clock: synchronous ticks make the
            # matrix deterministic-ish and keep the loop single-threaded
            deadline = time.monotonic() + float(
                self.scenario.params.get("settle_timeout_s", 60)
            )
            while time.monotonic() < deadline:
                self.autoscaler.tick()
                status = self.autoscaler.status()
                with self._lock:
                    quiet = self._load_left == 0
                if (quiet and status["scale_ups"] > 0
                        and status["replicas"]
                        == self.autoscaler.policy.min_replicas):
                    self.settled.set()
                    return
                time.sleep(0.1)

        plans = (
            [(i, 0.0, 0.12) for i in range(self.base)]
            + [(self.base + i, 0.5, 0.01) for i in range(self.burst)]
            + [(self.base + self.burst + i, 1.4, 0.1)
               for i in range(self.tail)]
        )
        return [controller] + [
            (lambda p=p: load(*p)) for p in plans
        ]

    def check(self, result):
        result.assert_clean()  # zero client-visible errors, no wedges
        for index in range(self.n):
            want = list(np.cumsum(self.values[index]))
            assert_byte_exact(
                self.delivered[index], want,
                label=f"sequence {2000 + index}",
            )
        status = self.autoscaler.status()
        assert status["scale_ups"] >= 1, "the ramp never scaled up"
        assert self.settled.is_set(), (
            f"fleet never converged back to the floor: {status}"
        )
        assert status["scale_downs"] == status["scale_ups"]
        # every scale-down went through drain (the launcher's only
        # retire path), and nothing was applied twice anywhere —
        # sequences caught on a retiring replica migrated through its
        # tier and resumed, they were not replayed from scratch
        self.ledger.assert_exactly_once()
        assert (
            self.registry.get("ctpu_autoscale_scale_ups_total", None)
            == status["scale_ups"]
        )
        assert self.registry.get("ctpu_autoscale_replicas", None) == 1

    def close(self):
        self.autoscaler.close()
        self.client.close()
        self.pool.close()
        for handle in self.autoscaler.replicas():
            try:
                handle.server.stop()
            except Exception:
                pass
            handle.tier.close()


def test_autoscale_absorbs_diurnal_ramp():
    scenario = ChaosScenario(
        "autoscale-ramp", [], seed=31,
        base=1, burst=6, tail=1, steps=10,
    )
    ChaosMatrix([scenario]).run(_AutoscaleRampFixture, join_timeout_s=180)


@pytest.mark.slow
def test_autoscale_diurnal_ramp_soak():
    """Scaled ramp for `make soak`: a 10x burst over more sessions and
    longer sequences — repetition is what finds the drain/retire vs
    sticky-lease races."""
    matrix = ChaosMatrix([
        ChaosScenario(
            f"autoscale-ramp-{seed}", [], seed=seed,
            base=2, burst=10, tail=3, steps=12, settle_timeout_s=120,
        )
        for seed in (7, 19)
    ])
    matrix.run(_AutoscaleRampFixture, join_timeout_s=600)
