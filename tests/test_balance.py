"""Replica-set balancing (client_tpu.balance) under real injected chaos.

Unit layers: policy selection, pool health/breaker/exclusion routing, the
resilience failover loop's rotation and budget semantics.  The acceptance
scenario runs three real in-process servers behind the replicated client,
kills one mid-load through the chaos TCP proxy and drains another, and
requires zero client-visible errors, all traffic converging on the
survivor (per-endpoint routed counters prove it), and a shared-trace-id
record of the failover hop.
"""

import threading
import time

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
import client_tpu.http as httpclient
from client_tpu.balance import (
    AsyncReplicatedClient,
    EndpointPool,
    LeastInflight,
    PowerOfTwoChoices,
    ReplicatedClient,
    RoundRobin,
    Weighted,
    make_policy,
)
from client_tpu.balance.pool import Endpoint
from client_tpu.resilience import (
    CircuitBreaker,
    CircuitBreakerRegistry,
    NoHealthyEndpointError,
    RetryPolicy,
    call_with_failover,
)
from client_tpu.serve import Model, Server, TensorSpec
from client_tpu.serve.metrics import BalancerMetricsObserver, Registry
from client_tpu.testing.faults import FaultProxy
from client_tpu.tracing import ClientTracer, read_trace_file
from client_tpu.utils import (
    SERVER_NOT_READY,
    SERVER_READY,
    SERVER_UNREACHABLE,
    InferenceServerException,
)


def _echo_model(name="echo", fn=None):
    def echo(inputs, params, ctx):
        return {"OUT": inputs["IN"]}

    return Model(
        name,
        inputs=[TensorSpec("IN", "INT32", [-1, 4])],
        outputs=[TensorSpec("OUT", "INT32", [-1, 4])],
        fn=fn or echo,
        max_batch_size=8,
    )


def _echo_inputs(mod):
    data = np.arange(4, dtype=np.int32).reshape(1, 4)
    inp = mod.InferInput("IN", [1, 4], "INT32")
    inp.set_data_from_numpy(data)
    return [inp], data


def _fast_policy(**kw):
    kw.setdefault("max_attempts", 6)
    kw.setdefault("initial_backoff_s", 0.02)
    kw.setdefault("max_backoff_s", 0.1)
    return RetryPolicy(**kw)


def _endpoints(n):
    return [Endpoint(f"ep{i}") for i in range(n)]


# -- policies ----------------------------------------------------------------


class TestPolicies:
    def test_round_robin_cycles(self):
        eps = _endpoints(3)
        policy = RoundRobin()
        picks = [policy.pick(eps) for _ in range(6)]
        assert sorted(p.url for p in picks) == sorted(
            [e.url for e in eps] * 2
        )

    def test_least_inflight_picks_min(self):
        eps = _endpoints(3)
        eps[0].inflight = 5
        eps[1].inflight = 1
        eps[2].inflight = 3
        policy = LeastInflight()
        assert all(policy.pick(eps) is eps[1] for _ in range(4))

    def test_least_inflight_rotates_ties(self):
        eps = _endpoints(3)
        policy = LeastInflight()
        picks = {policy.pick(eps).url for _ in range(6)}
        assert picks == {e.url for e in eps}

    def test_power_of_two_prefers_less_loaded(self):
        import random

        eps = _endpoints(2)
        eps[0].inflight = 10
        policy = PowerOfTwoChoices(rng=random.Random(7))
        assert all(policy.pick(eps) is eps[1] for _ in range(20))

    def test_weighted_respects_zero_weight(self):
        import random

        eps = _endpoints(3)
        eps[1].weight = 0.0
        policy = Weighted(rng=random.Random(3))
        picks = [policy.pick(eps) for _ in range(200)]
        assert eps[1] not in picks
        assert eps[0] in picks and eps[2] in picks

    def test_make_policy_rejects_unknown(self):
        with pytest.raises(InferenceServerException, match="unknown"):
            make_policy("fastest-wins")
        assert make_policy("power-of-two").name == "power-of-two"
        rr = RoundRobin()
        assert make_policy(rr) is rr


# -- pool routing ------------------------------------------------------------


class TestEndpointPool:
    def test_lease_skips_drained_endpoint(self):
        pool = EndpointPool(["a", "b", "c"])
        pool.set_state("b", SERVER_NOT_READY)
        for _ in range(9):
            lease = pool.lease()
            assert lease.url != "b"
            lease.success()

    def test_lease_accounts_inflight(self):
        pool = EndpointPool(["a", "b"], policy="least-inflight")
        l1 = pool.lease()
        l2 = pool.lease()
        assert {l1.url, l2.url} == {"a", "b"}  # spread by inflight
        assert all(s["inflight"] == 1 for s in pool.snapshot())
        l1.success()
        l2.failure(ConnectionResetError("x"), retryable=True)
        assert all(s["inflight"] == 0 for s in pool.snapshot())

    def test_lease_prefers_fresh_then_wraps(self):
        pool = EndpointPool(["a", "b"])
        lease = pool.lease(excluded=("a",))
        assert lease.url == "b"
        assert lease.last_candidate  # 'b' was the only fresh candidate
        lease.success()
        wrapped = pool.lease(excluded=("a", "b"))
        assert wrapped.last_candidate
        wrapped.success()

    def test_all_drained_raises(self):
        pool = EndpointPool(["a", "b"])
        pool.set_state("a", SERVER_NOT_READY)
        pool.set_state("b", SERVER_UNREACHABLE)
        with pytest.raises(NoHealthyEndpointError):
            pool.lease()

    def test_open_circuit_is_skipped_then_half_open_probes(self):
        pool = EndpointPool(
            ["a", "b"], failure_threshold=1, reset_timeout_s=0.08
        )
        pool.lease(excluded=("b",)).failure(
            ConnectionResetError("down"), retryable=True
        )
        assert pool.breakers.get("a").state == CircuitBreaker.OPEN
        for _ in range(4):  # open circuit never routed
            lease = pool.lease()
            assert lease.url == "b"
            lease.success()
        time.sleep(0.1)
        # cooldown passed: 'a' may be probed again (half-open), and its
        # probe succeeding closes the circuit
        seen = set()
        for _ in range(6):
            lease = pool.lease()
            seen.add(lease.url)
            lease.success()
        assert seen == {"a", "b"}
        assert pool.breakers.get("a").state == CircuitBreaker.CLOSED

    def test_every_circuit_open_raises(self):
        pool = EndpointPool(["a"], failure_threshold=1, reset_timeout_s=60.0)
        pool.lease().failure(ConnectionResetError("down"), retryable=True)
        with pytest.raises(NoHealthyEndpointError, match="open"):
            pool.lease()

    def test_outcome_marks_unreachable_only_while_probing(self):
        pool = EndpointPool(["a", "b"])
        pool.lease(excluded=("b",)).failure(
            ConnectionResetError("x"), retryable=True
        )
        assert pool.states()["a"] == SERVER_READY  # no prober: breaker only
        states = {"a": SERVER_READY, "b": SERVER_READY}
        pool.start_probes(lambda url: states[url], interval_s=30.0)
        pool.lease(excluded=("b",)).failure(
            ConnectionResetError("x"), retryable=True
        )
        assert pool.states()["a"] == SERVER_UNREACHABLE
        pool.close()

    def test_probe_loop_feeds_state_machine(self):
        states = {"a": SERVER_READY, "b": SERVER_READY}
        pool = EndpointPool(["a", "b"])
        pool.start_probes(lambda url: states[url], interval_s=0.02)
        states["b"] = SERVER_NOT_READY  # drain observed by probe
        deadline = time.monotonic() + 5
        while (
            pool.states()["b"] != SERVER_NOT_READY
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert pool.states()["b"] == SERVER_NOT_READY
        states["b"] = SERVER_READY  # recovery observed too
        while (
            pool.states()["b"] != SERVER_READY
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert pool.states()["b"] == SERVER_READY
        pool.close()

    def test_shared_breaker_registry_across_pools(self):
        registry = CircuitBreakerRegistry(
            failure_threshold=1, reset_timeout_s=60.0
        )
        pool1 = EndpointPool(["a", "b"], breakers=registry)
        EndpointPool(["a", "c"], breakers=registry)
        pool1.lease(excluded=("b",)).failure(
            ConnectionResetError("x"), retryable=True
        )
        # the same endpoint's breaker is shared; others are independent
        assert registry.states() == {
            "a": CircuitBreaker.OPEN,
            "b": CircuitBreaker.CLOSED,
            "c": CircuitBreaker.CLOSED,
        }

    def test_construction_errors_are_not_retryable_routing_errors(self):
        # config mistakes raise ValueError, not the transient 503-status
        # NoHealthyEndpointError a retry layer would spin on
        with pytest.raises(ValueError, match="duplicate"):
            EndpointPool(["a", "a"])
        with pytest.raises(ValueError, match="empty"):
            EndpointPool([])

    def test_answered_errors_never_mark_unreachable(self):
        """An answered 503/429 (overload shed, drain) is evidence the
        server is ALIVE: only connection-level failures may flip the
        health state, even while probing is active."""
        pool = EndpointPool(["a", "b"])
        pool.start_probes(lambda url: SERVER_READY, 30.0)
        shed = InferenceServerException("server overloaded", status="503")
        pool.lease(excluded=("b",)).failure(shed, retryable=True)
        assert pool.states()["a"] == SERVER_READY
        dead = InferenceServerException(
            "connection refused", status="503",
            debug_details=ConnectionRefusedError("refused"),
        )
        pool.lease(excluded=("b",)).failure(dead, retryable=True)
        assert pool.states()["a"] == SERVER_UNREACHABLE
        pool.close()


# -- the failover loop (pure, no sockets) ------------------------------------


class _FakeLease:
    def __init__(self, key, last_candidate=False):
        self.key = key
        self.last_candidate = last_candidate
        self.outcome = None

    def success(self):
        self.outcome = "ok"

    def failure(self, exc, retryable):
        self.outcome = ("fail", retryable)


class TestFailoverLoop:
    def test_rotates_to_fresh_replica_immediately(self):
        leases = {}

        def route(excluded):
            url = "b" if "a" in excluded else "a"
            leases[url] = _FakeLease(url)
            return leases[url]

        def fn(lease, timeout_s):
            if lease.key == "a":
                raise ConnectionRefusedError("a is down")
            return "served-by-" + lease.key

        policy = _fast_policy(jitter=False, initial_backoff_s=0.5)
        t0 = time.monotonic()
        assert call_with_failover(fn, policy, route) == "served-by-b"
        # the hop to the fresh replica must NOT pay the 0.5s backoff
        assert time.monotonic() - t0 < 0.2
        assert leases["a"].outcome == ("fail", True)
        assert leases["b"].outcome == "ok"

    def test_wrapped_rotation_backs_off(self):
        calls = []

        def route(excluded):
            return _FakeLease("only", last_candidate=True)

        def fn(lease, timeout_s):
            calls.append(time.monotonic())
            if len(calls) < 3:
                raise ConnectionRefusedError("flaky")
            return "ok"

        policy = _fast_policy(jitter=False, initial_backoff_s=0.05,
                              max_backoff_s=0.05)
        assert call_with_failover(fn, policy, route) == "ok"
        assert len(calls) == 3
        assert calls[-1] - calls[0] >= 0.08  # two backoffs applied

    def test_non_retryable_fails_without_rotation(self):
        routed = []

        def route(excluded):
            lease = _FakeLease(f"ep{len(routed)}")
            routed.append(lease)
            return lease

        def fn(lease, timeout_s):
            raise InferenceServerException("bad input", status="400")

        with pytest.raises(InferenceServerException, match="bad input"):
            call_with_failover(fn, _fast_policy(), route)
        assert len(routed) == 1
        assert routed[0].outcome == ("fail", False)

    def test_no_healthy_endpoint_is_retried_then_raised(self):
        calls = []

        def route(excluded):
            calls.append(excluded)
            raise NoHealthyEndpointError("all down")

        policy = _fast_policy(max_attempts=3, jitter=False,
                              initial_backoff_s=0.01)
        with pytest.raises(NoHealthyEndpointError):
            call_with_failover(lambda lease, t: None, policy, route)
        assert len(calls) == 3

    def test_deadline_bounds_failover_storm(self):
        def route(excluded):
            return _FakeLease("ep", last_candidate=True)

        def fn(lease, timeout_s):
            raise ConnectionRefusedError("down")

        policy = RetryPolicy(
            max_attempts=1000, initial_backoff_s=0.02, max_backoff_s=0.05,
            jitter=False, deadline_s=0.3,
        )
        t0 = time.monotonic()
        with pytest.raises(ConnectionRefusedError):
            call_with_failover(fn, policy, route)
        assert time.monotonic() - t0 < 1.0


# -- replicated clients over real servers ------------------------------------


def _start_servers(n, grpc=False):
    return [
        Server(
            models=[_echo_model()], with_default_models=False,
            grpc_port=0 if grpc else None,
        ).start()
        for _ in range(n)
    ]


_FAST_RECONNECT = [
    ("grpc.initial_reconnect_backoff_ms", 50),
    ("grpc.min_reconnect_backoff_ms", 50),
    ("grpc.max_reconnect_backoff_ms", 100),
]


class TestReplicatedClient:
    def test_http_round_robin_spreads_and_reports(self):
        servers = _start_servers(2)
        registry = Registry()
        pool = EndpointPool(
            [s.http_address for s in servers],
            observer=BalancerMetricsObserver(registry),
        )
        try:
            with ReplicatedClient(
                pool, transport="http", probe_interval_s=None
            ) as client:
                inputs, data = _echo_inputs(httpclient)
                for _ in range(6):
                    result = client.infer("echo", inputs)
                    np.testing.assert_array_equal(
                        result.as_numpy("OUT"), data
                    )
                for s in servers:
                    assert registry.get(
                        "ctpu_client_routed_total",
                        {"endpoint": s.http_address},
                    ) == 3
                assert client.is_server_ready()
                assert client.is_model_ready("echo")
                meta = client.get_server_metadata()
                assert "name" in meta
        finally:
            for s in servers:
                s.stop()

    def test_grpc_failover_records_hop_on_one_trace(self):
        servers = _start_servers(2, grpc=True)
        proxy = FaultProxy(servers[0].grpc_address)
        tracer = ClientTracer()
        try:
            with ReplicatedClient(
                [proxy.address, servers[1].grpc_address],
                transport="grpc",
                probe_interval_s=None,  # the request itself must discover
                tracer=tracer,
                retry_policy=_fast_policy(jitter=False),
                channel_args=_FAST_RECONNECT,
            ) as client:
                inputs, data = _echo_inputs(grpcclient)
                result = client.infer("echo", inputs)  # warm both channels
                proxy.refuse_connections(True)
                proxy.kill_active()
                for _ in range(4):
                    result = client.infer("echo", inputs)
                    np.testing.assert_array_equal(
                        result.as_numpy("OUT"), data
                    )
                hops = [
                    t.attempt_endpoints()
                    for t in tracer.traces
                    if len(set(t.attempt_endpoints())) > 1
                ]
                assert hops, "no trace recorded a failover hop"
                assert hops[0][0] == proxy.address
                assert hops[0][-1] == servers[1].grpc_address
        finally:
            proxy.close()
            for s in servers:
                s.stop()

    def test_streaming_pins_one_healthy_replica(self):
        servers = _start_servers(2, grpc=True)
        try:
            with ReplicatedClient(
                [s.grpc_address for s in servers],
                transport="grpc",
                probe_interval_s=None,
            ) as client:
                events = []
                got = threading.Event()

                def callback(result, error):
                    events.append((result, error))
                    got.set()

                client.start_stream(callback)
                pinned = client._stream_lease.url
                assert pinned in [s.grpc_address for s in servers]
                inputs, data = _echo_inputs(grpcclient)
                client.async_stream_infer("echo", inputs)
                assert got.wait(timeout=10)
                result, error = events[0]
                assert error is None
                np.testing.assert_array_equal(result.as_numpy("OUT"), data)
                client.stop_stream()
                assert all(
                    s["inflight"] == 0 for s in client.pool.snapshot()
                )
        finally:
            for s in servers:
                s.stop()

    def test_aio_http_failover(self):
        import asyncio

        import client_tpu.http.aio as aiohttpclient

        servers = _start_servers(2)
        proxy = FaultProxy(servers[0].http_address)

        async def flow():
            client = AsyncReplicatedClient(
                [proxy.address, servers[1].http_address],
                transport="http",
                retry_policy=_fast_policy(jitter=False),
            )
            try:
                inputs, data = _echo_inputs(aiohttpclient)
                result = await client.infer("echo", inputs)
                proxy.refuse_connections(True)
                proxy.kill_active()
                for _ in range(4):
                    result = await client.infer("echo", inputs)
                    np.testing.assert_array_equal(
                        result.as_numpy("OUT"), data
                    )
                states = await client.refresh_states()
                assert states[proxy.address] == SERVER_UNREACHABLE
                assert states[servers[1].http_address] == SERVER_READY
                assert await client.is_server_ready()
            finally:
                await client.close()

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(flow())
        finally:
            loop.close()
            proxy.close()
            for s in servers:
                s.stop()


class TestTimeoutsAndOwnership:
    def test_http_client_timeout_s_bounds_the_attempt(self):
        """The HTTP clients' new client-side per-request timeout: a stalled
        endpoint must fail the attempt at the bound, not at the pool-level
        60s default."""
        server = _start_servers(1)[0]
        proxy = FaultProxy(server.http_address)
        proxy.set_delay(3.0)  # hold every connection before bridging
        try:
            with httpclient.InferenceServerClient(proxy.address) as client:
                inputs, _ = _echo_inputs(httpclient)
                t0 = time.monotonic()
                with pytest.raises(InferenceServerException):
                    client.infer("echo", inputs, client_timeout_s=0.2)
                assert time.monotonic() - t0 < 1.5
        finally:
            proxy.close()
            server.stop()

    def test_replicated_http_times_out_stalled_replica_and_fails_over(self):
        """A replica that accepts connections but stalls must not eat the
        whole failover budget: the per-attempt timeout aborts it and the
        retry lands on the healthy replica."""
        servers = _start_servers(2)
        proxy = FaultProxy(servers[0].http_address)
        proxy.set_delay(10.0)  # black-hole-ish: accepts, then stalls
        try:
            with ReplicatedClient(
                [proxy.address, servers[1].http_address],
                transport="http",
                policy="round-robin",
                probe_interval_s=None,
                retry_policy=RetryPolicy(
                    max_attempts=4, initial_backoff_s=0.02,
                    max_backoff_s=0.1, deadline_s=5.0,
                ),
            ) as client:
                inputs, data = _echo_inputs(httpclient)
                t0 = time.monotonic()
                for _ in range(2):  # round-robin guarantees a stalled pick
                    result = client.infer(
                        "echo", inputs, client_timeout_s=0.3
                    )
                    np.testing.assert_array_equal(
                        result.as_numpy("OUT"), data
                    )
                assert time.monotonic() - t0 < 4.0
        finally:
            proxy.close()
            for s in servers:
                s.stop()

    def test_caller_owned_pool_survives_client_close(self):
        servers = _start_servers(2)
        pool = EndpointPool([s.http_address for s in servers])
        try:
            client = ReplicatedClient(
                pool, transport="http", probe_interval_s=None
            )
            inputs, _ = _echo_inputs(httpclient)
            client.infer("echo", inputs)
            client.close()
            # the shared pool is untouched: still routable, still armable
            lease = pool.lease()
            lease.success()
            assert pool.start_probes(lambda url: SERVER_READY,
                                     interval_s=30.0) is True
        finally:
            pool.close()
            for s in servers:
                s.stop()

    def test_owned_pool_probes_stop_on_close(self):
        servers = _start_servers(1)
        client = ReplicatedClient(
            [servers[0].http_address], transport="http",
            probe_interval_s=0.05,
        )
        try:
            prober = client.pool._prober
            assert prober is not None and prober.is_alive()
            client.close()
            assert client.pool._prober is None
            assert not prober.is_alive()
        finally:
            servers[0].stop()

    def test_pool_close_is_rearmable(self):
        pool = EndpointPool(["a"])
        assert pool.start_probes(lambda url: SERVER_READY, 30.0) is True
        assert pool.start_probes(lambda url: SERVER_READY, 30.0) is False
        pool.close()
        assert pool.start_probes(lambda url: SERVER_READY, 30.0) is True
        pool.close()

    def test_no_unreachable_marking_after_probes_stop(self):
        """Once close() stops the prober, a transient retryable failure
        must not strand an endpoint UNREACHABLE (nothing is left to
        recover it; the breaker alone gates then)."""
        pool = EndpointPool(["a", "b"])
        pool.start_probes(lambda url: SERVER_READY, 30.0)
        pool.close()
        pool.lease(excluded=("b",)).failure(
            ConnectionResetError("x"), retryable=True
        )
        assert pool.states()["a"] == SERVER_READY

    def test_breaker_observer_may_read_pool_during_lease(self):
        """lease() delivers breaker transitions OUTSIDE the pool lock: an
        observer that looks back at the pool must not deadlock."""
        seen = []
        pool_ref = []

        class PoolReadingObserver:
            def on_state_change(self, old, new):
                # would deadlock if delivered under the pool lock
                seen.append((new, pool_ref[0].states()))

        registry = CircuitBreakerRegistry(
            failure_threshold=1, reset_timeout_s=0.05,
            observer_factory=lambda endpoint: PoolReadingObserver(),
        )
        pool = EndpointPool(["a"], breakers=registry)
        pool_ref.append(pool)
        pool.lease().failure(ConnectionResetError("x"), retryable=True)
        time.sleep(0.06)
        result = []
        worker = threading.Thread(
            target=lambda: result.append(pool.lease())
        )
        worker.start()
        worker.join(timeout=5)
        assert not worker.is_alive(), "lease() deadlocked on the observer"
        result[0].success()
        assert any(state == "half-open" for state, _ in seen)


# -- stream-lease lifecycle (satellite audit) --------------------------------


class _FailingStreamClient:
    """Per-endpoint stub whose start_stream always raises."""

    def __init__(self, url, **kwargs):
        self.url = url

    def start_stream(self, callback, **kwargs):
        raise InferenceServerException("stream refused", status="400")

    def stop_stream(self, cancel_requests=False):
        pass

    def close(self):
        pass


class TestStreamLeaseLifecycle:
    def test_sync_start_stream_failure_releases_lease(self):
        client = ReplicatedClient(
            ["a", "b"], transport="grpc", probe_interval_s=None,
            client_factory=_FailingStreamClient,
        )
        try:
            with pytest.raises(InferenceServerException, match="refused"):
                client.start_stream(lambda result, error: None)
            # the lease did not leak: no inflight slot held, no pinned
            # stream recorded, and the stream can be attempted again
            assert all(s["inflight"] == 0 for s in client.pool.snapshot())
            assert client._stream_lease is None
            with pytest.raises(InferenceServerException, match="refused"):
                client.start_stream(lambda result, error: None)
            assert all(s["inflight"] == 0 for s in client.pool.snapshot())
        finally:
            client.close()

    def test_aio_abandoned_generator_releases_lease_on_aclose(self):
        """The aclose() regression: an aio stream that is created but
        never iterated must still release its lease when closed —
        a bare generator's ``finally`` never runs for a body that never
        started."""
        import asyncio

        class _StubAioStream:
            def __init__(self, url, **kwargs):
                self.url = url

            def stream_infer(self, inputs_iterator, **kwargs):
                async def gen():
                    yield None, None

                return gen()

            async def close(self):
                pass

        async def flow():
            client = AsyncReplicatedClient(
                ["a", "b"], transport="grpc",
                client_factory=_StubAioStream,
            )
            try:
                stream = client.stream_infer(iter(()))
                assert any(
                    s["inflight"] == 1 for s in client.pool.snapshot()
                )
                await stream.aclose()  # never iterated
                assert all(
                    s["inflight"] == 0 for s in client.pool.snapshot()
                )
                # partially consumed then closed: released exactly once
                stream = client.stream_infer(iter(()))
                await stream.__anext__()
                await stream.aclose()
                assert all(
                    s["inflight"] == 0 for s in client.pool.snapshot()
                )
            finally:
                await client.close()

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(flow())
        finally:
            loop.close()

    def test_aio_stream_infer_failure_releases_lease(self):
        import asyncio

        class _RaisingAio:
            def __init__(self, url, **kwargs):
                pass

            def stream_infer(self, inputs_iterator, **kwargs):
                raise InferenceServerException("no stream", status="400")

            async def close(self):
                pass

        async def flow():
            client = AsyncReplicatedClient(
                ["a"], transport="grpc", client_factory=_RaisingAio
            )
            try:
                with pytest.raises(InferenceServerException, match="no"):
                    client.stream_infer(iter(()))
                assert all(
                    s["inflight"] == 0 for s in client.pool.snapshot()
                )
            finally:
                await client.close()

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(flow())
        finally:
            loop.close()


# -- drain vs death distinction (satellite) ----------------------------------


class TestServerStateVerb:
    def test_http_and_grpc_three_states(self):
        server = Server(
            models=[_echo_model()], with_default_models=False, grpc_port=0
        ).start()
        http = httpclient.InferenceServerClient(server.http_address)
        grpc_c = grpcclient.InferenceServerClient(server.grpc_address)
        try:
            assert http.server_state() == SERVER_READY
            assert grpc_c.server_state() == SERVER_READY
            server.engine.drain(timeout_s=5)  # frontends stay up
            assert http.server_state() == SERVER_NOT_READY
            assert grpc_c.server_state() == SERVER_NOT_READY
            assert http.is_server_ready() is False  # bool contract intact
            assert grpc_c.is_server_ready() is False
        finally:
            http.close()
            grpc_c.close()
            server.stop()
        # frontends gone: the same probes now answer UNREACHABLE
        http = httpclient.InferenceServerClient(server.http_address)
        try:
            assert http.server_state() == SERVER_UNREACHABLE
        finally:
            http.close()

    def test_aio_three_states(self):
        import asyncio

        import client_tpu.grpc.aio as aiogrpc
        import client_tpu.http.aio as aiohttpclient

        server = Server(
            models=[_echo_model()], with_default_models=False, grpc_port=0
        ).start()

        async def flow():
            async with aiohttpclient.InferenceServerClient(
                server.http_address
            ) as http, aiogrpc.InferenceServerClient(
                server.grpc_address
            ) as grpc_c:
                assert await http.server_state() == SERVER_READY
                assert await grpc_c.server_state() == SERVER_READY
                server.engine.drain(timeout_s=5)
                assert await http.server_state() == SERVER_NOT_READY
                assert await grpc_c.server_state() == SERVER_NOT_READY

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(flow())
        finally:
            loop.close()
            server.stop()


# -- acceptance: chaos over three replicas -----------------------------------


class TestChaosReplicaSet:
    def test_kill_one_drain_one_under_load(self, tmp_path):
        """Three replicas under concurrent load; one dies mid-load (chaos
        proxy), one drains gracefully.  Zero client-visible errors, all
        traffic converges on the survivor, metrics and traces prove it."""
        servers = _start_servers(3)
        proxy = FaultProxy(servers[0].http_address)  # replica A: the victim
        url_a = proxy.address
        url_b = servers[1].http_address  # replica B: drained mid-load
        url_c = servers[2].http_address  # replica C: survivor
        trace_file = str(tmp_path / "trace.jsonl")
        registry = Registry()
        pool = EndpointPool(
            [url_a, url_b, url_c],
            policy="least-inflight",
            observer=BalancerMetricsObserver(registry),
            failure_threshold=2,
            reset_timeout_s=60.0,
        )
        tracer = ClientTracer(trace_file=trace_file, max_traces=10000)
        client = ReplicatedClient(
            pool,
            transport="http",
            tracer=tracer,
            probe_interval_s=0.05,
            retry_policy=RetryPolicy(
                max_attempts=8, initial_backoff_s=0.02, max_backoff_s=0.2,
                deadline_s=20.0,
            ),
        )
        errors = []
        done = [0]
        lock = threading.Lock()

        def worker():
            inputs, data = _echo_inputs(httpclient)
            for _ in range(40):
                try:
                    result = client.infer("echo", inputs)
                    np.testing.assert_array_equal(
                        result.as_numpy("OUT"), data
                    )
                except Exception as exc:  # noqa: BLE001 - recorded, asserted
                    with lock:
                        errors.append(exc)
                with lock:
                    done[0] += 1

        threads = [threading.Thread(target=worker) for _ in range(4)]
        try:
            for t in threads:
                t.start()
            # let all three replicas take traffic, then kill A hard
            deadline = time.monotonic() + 10
            while done[0] < 30 and time.monotonic() < deadline:
                time.sleep(0.005)
            proxy.refuse_connections(True)
            proxy.kill_active()
            # and drain B gracefully while requests are still flowing
            time.sleep(0.1)
            assert servers[1].engine.drain(timeout_s=10) is True
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)

            # 1) zero non-retryable client errors: every request landed
            assert errors == []
            assert done[0] == 160

            # 2) the pool learned both conditions, each with the right state
            deadline = time.monotonic() + 5
            while (
                client.states() != {
                    url_a: SERVER_UNREACHABLE,
                    url_b: SERVER_NOT_READY,
                    url_c: SERVER_READY,
                }
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert client.states() == {
                url_a: SERVER_UNREACHABLE,
                url_b: SERVER_NOT_READY,
                url_c: SERVER_READY,
            }

            # 3) convergence: new traffic routes ONLY to the survivor
            def routed(url):
                return registry.get(
                    "ctpu_client_routed_total", {"endpoint": url}
                ) or 0

            before = {u: routed(u) for u in (url_a, url_b, url_c)}
            inputs, _ = _echo_inputs(httpclient)
            for _ in range(10):
                client.infer("echo", inputs)
            assert routed(url_a) == before[url_a]
            assert routed(url_b) == before[url_b]
            assert routed(url_c) == before[url_c] + 10
            # every replica carried load before the chaos
            assert before[url_a] > 0 and before[url_b] > 0

            # 4) the kill produced recorded failovers off replica A
            assert (
                registry.get(
                    "ctpu_client_failovers_total", {"endpoint": url_a}
                )
                >= 1
            )
            # and the endpoint-state gauge mirrors the pool view
            assert registry.get(
                "ctpu_client_endpoint_state", {"endpoint": url_a}
            ) == 2
            assert registry.get(
                "ctpu_client_endpoint_state", {"endpoint": url_b}
            ) == 1

            # 5) the failover hop is on the trace timeline: some span holds
            # consecutive attempts on different endpoints under ONE trace id
            hop_traces = [
                t for t in tracer.traces
                if len(set(t.attempt_endpoints())) > 1
            ]
            assert hop_traces, "no trace recorded a failover hop"
            hop = hop_traces[0]
            assert hop.attempt_endpoints()[0] != hop.attempt_endpoints()[-1]
            # the exported records carry the same trace id and endpoints
            exported = [
                r for r in read_trace_file(trace_file)
                if r["trace_id"] == hop.trace_id
            ]
            assert len(exported) == 1
            starts = [
                t for t in exported[0]["timestamps"]
                if t["name"] == "CLIENT_ATTEMPT_START"
            ]
            assert len({t.get("endpoint") for t in starts}) > 1
        finally:
            client.close()
            proxy.close()
            for s in servers:
                s.stop()

    def test_failover_hop_joins_server_span_under_one_trace_id(
        self, tmp_path
    ):
        """The surviving replica's server span joins the client's failover
        span under the same trace id — the hop AND the successful landing
        are one timeline."""
        servers = _start_servers(2)
        proxy = FaultProxy(servers[0].http_address)
        trace_file = str(tmp_path / "trace.jsonl")
        with httpclient.InferenceServerClient(servers[1].http_address) as c:
            c.update_trace_settings(settings={
                "trace_level": ["TIMESTAMPS"],
                "trace_rate": "1",
                "trace_count": "-1",
                "trace_file": trace_file,
            })
        tracer = ClientTracer(trace_file=trace_file)
        client = ReplicatedClient(
            [proxy.address, servers[1].http_address],
            transport="http",
            policy="round-robin",
            probe_interval_s=None,
            tracer=tracer,
            retry_policy=_fast_policy(jitter=False),
        )
        try:
            proxy.refuse_connections(True)
            inputs, data = _echo_inputs(httpclient)
            hop_trace = None
            for _ in range(4):  # round-robin lands on the dead replica soon
                result = client.infer("echo", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUT"), data)
                for t in tracer.traces:
                    if len(set(t.attempt_endpoints())) > 1:
                        hop_trace = t
                if hop_trace is not None:
                    break
            assert hop_trace is not None
            # the server appends its span AFTER sending the response, so
            # the client can observe success before the record lands —
            # poll briefly instead of racing the handler's final write
            deadline = time.monotonic() + 2.0
            while True:
                joined = [
                    r for r in read_trace_file(trace_file)
                    if r["trace_id"] == hop_trace.trace_id
                ]
                sources = {r["source"] for r in joined}
                if sources == {"client", "server"} \
                        or time.monotonic() > deadline:
                    break
                time.sleep(0.02)
            assert sources == {"client", "server"}
            client_rec = next(r for r in joined if r["source"] == "client")
            server_rec = next(r for r in joined if r["source"] == "server")
            assert server_rec["parent_span_id"] == client_rec["span_id"]
            endpoints = [
                t.get("endpoint")
                for t in client_rec["timestamps"]
                if t["name"] == "CLIENT_ATTEMPT_START"
            ]
            assert endpoints[0] == proxy.address  # the failed first attempt
            assert endpoints[-1] == servers[1].http_address  # the landing
        finally:
            client.close()
            proxy.close()
            for s in servers:
                s.stop()
