"""Continuous-batching LM decode (serve/models/continuous.py): batched
lanes must reproduce serial greedy decoding exactly, reuse slots, survive
cancels, and scale the serving path over concurrent streams."""

import queue
import threading
import time

import numpy as np
import pytest

import jax

from client_tpu.serve.models import transformer as tfm
from client_tpu.serve.models.continuous import (
    BatchedLmRunner,
    ContinuousLmScheduler,
)

CFG = tfm.TransformerConfig(
    vocab_size=128,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
    max_seq=48,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def _serial(params, prompt, n):
    return list(tfm.generate(params, CFG, prompt, n, readback_depth=0))


def _collect(q):
    out = []
    while True:
        tok = q.get(timeout=60)
        if tok is ContinuousLmScheduler.CLOSE:
            return out
        out.append(tok)


def test_concurrent_streams_match_serial(params):
    """Lanes with different prompts and lengths decode EXACTLY the serial
    greedy streams — heterogeneous positions share one batched tick."""
    sched = ContinuousLmScheduler(params, CFG, max_slots=4)
    try:
        prompts = [[1, 2, 3], [7, 9], [5], [11, 3, 2, 8]]
        lengths = [6, 9, 4, 7]
        queues = [
            sched.submit(p, n)[0] for p, n in zip(prompts, lengths)
        ]
        got = [_collect(q) for q in queues]
        for p, n, tokens in zip(prompts, lengths, got):
            assert tokens == _serial(params, p, n), (p, n)
    finally:
        sched.close()


def test_slot_reuse_more_requests_than_lanes(params):
    sched = ContinuousLmScheduler(params, CFG, max_slots=2)
    try:
        prompts = [[i + 1, i + 2] for i in range(5)]
        queues = [sched.submit(p, 5)[0] for p in prompts]
        for p, q in zip(prompts, queues):
            assert _collect(q) == _serial(params, p, 5)
    finally:
        sched.close()


def test_cancel_frees_lane(params):
    sched = ContinuousLmScheduler(params, CFG, max_slots=1)
    try:
        q1, h1 = sched.submit([1, 2, 3], 30)
        assert q1.get(timeout=60) is not ContinuousLmScheduler.CLOSE
        sched.cancel(h1)
        # the single lane must come free for the next request
        q2, _ = sched.submit([4, 5], 4)
        assert _collect(q2) == _serial(params, [4, 5], 4)
    finally:
        sched.close()


def test_cancel_with_pending_queue(params):
    """cancel() must work by identity while other requests are PENDING —
    entry lists hold numpy prompts, so naive `in`/`remove` membership would
    raise numpy's ambiguous-truth ValueError (regression)."""
    sched = ContinuousLmScheduler(params, CFG, max_slots=1)
    try:
        q1, h1 = sched.submit([1, 2, 3], 20)
        q2, h2 = sched.submit([1, 2, 3], 6)  # same-shape prompt, queued
        q3, h3 = sched.submit([9], 6)        # different-shape prompt, queued
        assert q1.get(timeout=60) is not ContinuousLmScheduler.CLOSE
        sched.cancel(h1)   # active lane, pending entries present
        sched.cancel(h3)   # pending entry, removed by identity
        assert _collect(q2) == _serial(params, [1, 2, 3], 6)
    finally:
        sched.close()


def test_cancel_active_slot_closes_queue(params):
    """cancel() on an ADMITTED request must enqueue CLOSE on the slot's
    queue: a public-API consumer reading the queue directly (not the
    abandoning BatchedLmRunner generator) must never hang on get()."""
    sched = ContinuousLmScheduler(params, CFG, max_slots=1)
    try:
        q, h = sched.submit([1, 2, 3], 30)
        assert q.get(timeout=60) is not ContinuousLmScheduler.CLOSE
        sched.cancel(h)
        # drain whatever was in flight; the stream MUST terminate
        while True:
            tok = q.get(timeout=10)  # pre-fix: hangs forever here
            if tok is ContinuousLmScheduler.CLOSE:
                break
        sched.cancel(h)  # idempotent: double-cancel of a released lane
    finally:
        sched.close()


class _GatedPrefill:
    """Wraps a scheduler's jitted prefill so tests can hold the dispatch
    open and observe what the scheduler lock does meanwhile."""

    def __init__(self, real):
        self.real = real
        self.entered = threading.Event()
        self.release = threading.Event()

    def __call__(self, *args, **kwargs):
        self.entered.set()
        assert self.release.wait(timeout=60)
        return self.real(*args, **kwargs)


def test_submit_not_blocked_by_slow_prefill(params):
    """A slow (cold-compile) prefill must not head-of-line-block submit():
    the admission dispatch runs outside _cv (regression for the pre-fix
    _admit_locked, which held the condition lock across the compile)."""
    sched = ContinuousLmScheduler(params, CFG, max_slots=2)
    gate = _GatedPrefill(sched._prefill)
    sched._prefill = gate
    try:
        q1, _ = sched.submit([1, 2, 3], 4)
        assert gate.entered.wait(timeout=60)
        # scheduler thread is inside the prefill dispatch right now; the
        # lock must be free for new submissions and cancels
        t0 = time.monotonic()
        q2, h2 = sched.submit([4, 5], 3)
        sched.cancel(None)
        submit_latency = time.monotonic() - t0
        gate.release.set()
        assert submit_latency < 1.0, submit_latency
        assert _collect(q1) == _serial(params, [1, 2, 3], 4)
        assert _collect(q2) == _serial(params, [4, 5], 3)
    finally:
        gate.release.set()
        sched.close()


def test_cancel_during_prefill_closes_stream(params):
    """cancel() racing the (unlocked) prefill dispatch: the stream still
    terminates with CLOSE and the lane comes back free."""
    sched = ContinuousLmScheduler(params, CFG, max_slots=1)
    gate = _GatedPrefill(sched._prefill)
    sched._prefill = gate
    try:
        q1, h1 = sched.submit([1, 2, 3], 8)
        assert gate.entered.wait(timeout=60)
        sched.cancel(h1)  # mid-admission: entry popped, not yet placed
        gate.release.set()
        assert _collect(q1) == []  # closed without tokens, reader released
        q2, _ = sched.submit([4, 5], 3)
        assert _collect(q2) == _serial(params, [4, 5], 3)
    finally:
        gate.release.set()
        sched.close()


def test_cancel_twice_during_prefill_is_idempotent(params):
    """Double-cancel racing the unlocked prefill dispatch (round-5 audit):
    the first cancel marks the handle _CANCELLED, the second must be a
    no-op — and the lane still comes back free once _admit observes the
    marker and closes the stream."""
    sched = ContinuousLmScheduler(params, CFG, max_slots=1)
    gate = _GatedPrefill(sched._prefill)
    sched._prefill = gate
    try:
        q1, h1 = sched.submit([1, 2, 3], 8)
        assert gate.entered.wait(timeout=60)
        sched.cancel(h1)  # entry popped, not yet placed: marks _CANCELLED
        sched.cancel(h1)  # second cancel sees the marker: no-op, no crash
        gate.release.set()
        assert _collect(q1) == []
        sched.cancel(h1)  # post-close cancel of the marked handle: no-op
        q2, _ = sched.submit([4, 5], 3)
        assert _collect(q2) == _serial(params, [4, 5], 3)
    finally:
        gate.release.set()
        sched.close()


def test_submit_after_close_returns_closed_stream(params):
    """submit() on a closed scheduler must hand back an already-closed
    queue (reader gets CLOSE immediately) instead of queueing work no
    scheduler thread will ever admit."""
    sched = ContinuousLmScheduler(params, CFG, max_slots=1)
    sched.close()
    q, handle = sched.submit([1, 2, 3], 4)
    assert handle is None
    assert q.get(timeout=10) is ContinuousLmScheduler.CLOSE
    sched.cancel(handle)  # cancel of a rejected submit: no-op


def test_failing_prefill_does_not_strand_reader(params):
    """If the admission dispatch itself dies (device OOM / XLA failure on
    a cold compile), the popped entry's reader must still get CLOSE — it
    is in neither _pending nor a slot when the crash handler runs."""
    sched = ContinuousLmScheduler(params, CFG, max_slots=1)

    def exploding_prefill(*a, **kw):
        raise RuntimeError("XLA compile failed")

    sched._prefill = exploding_prefill
    try:
        q, _ = sched.submit([1, 2, 3], 4)
        assert _collect(q) == []  # stream closed, no tokens, no hang
    finally:
        sched.close()


def test_eos_stops_stream(params):
    """An eos_id token terminates the stream (still yielded) and frees
    the lane."""
    # find a token the model actually emits early for this prompt
    serial = _serial(params, [1, 2, 3], 4)
    eos = serial[1]
    sched = ContinuousLmScheduler(params, CFG, max_slots=1, eos_id=eos)
    try:
        q, _ = sched.submit([1, 2, 3], 10)
        got = _collect(q)
        assert got == serial[: serial.index(eos) + 1]
    finally:
        sched.close()


def test_batched_runner_stream(params):
    runner = BatchedLmRunner(params, CFG, max_slots=2)
    try:
        toks = list(runner.stream([3, 1], 5))
        assert toks == _serial(params, [3, 1], 5)
        # abandoning a stream mid-flight must not wedge the lane
        gen = runner.stream([2, 2], 20)
        next(gen)
        gen.close()
        toks = list(runner.stream([3, 1], 5))
        assert toks == _serial(params, [3, 1], 5)
    finally:
        runner.scheduler.close()


def test_grpc_batched_model_concurrent(params):
    """lm_streaming_batched over real gRPC: concurrent streams produce the
    same tokens as the serial lm_streaming model (same float weights —
    the batched model serves the shared float runner; int8 lives on as
    lm_streaming_int8)."""
    import client_tpu.grpc as grpcclient
    from client_tpu.serve import Server
    from client_tpu.serve.models import language_models

    with Server(
        models=language_models(), grpc_port=0, with_default_models=False
    ) as server:
        def run_stream(model, prompt, n):
            results = queue.Queue()
            client = grpcclient.InferenceServerClient(server.grpc_address)
            client.start_stream(
                callback=lambda result, error: results.put((result, error))
            )
            t_in = grpcclient.InferInput("TOKENS", [len(prompt)], "INT32")
            t_in.set_data_from_numpy(np.asarray(prompt, dtype=np.int32))
            m_in = grpcclient.InferInput("MAX_TOKENS", [1], "INT32")
            m_in.set_data_from_numpy(np.array([n], dtype=np.int32))
            client.async_stream_infer(
                model, [t_in, m_in], enable_empty_final_response=True
            )
            toks = []
            while True:
                r, e = results.get(timeout=120)
                assert e is None, e
                if r.get_response().parameters[
                    "triton_final_response"
                ].bool_param:
                    break
                toks.append(int(r.as_numpy("TOKEN")[0]))
            client.stop_stream()
            client.close()
            return toks

        prompts = [[1, 2, 3], [9, 9], [4, 5, 6, 7]]
        expected = [run_stream("lm_streaming", p, 5) for p in prompts]

        got = [None] * len(prompts)
        threads = [
            threading.Thread(
                target=lambda i=i, p=p: got.__setitem__(
                    i, run_stream("lm_streaming_batched", p, 5)
                )
            )
            for i, p in enumerate(prompts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert got == expected
