"""Pipeline parallelism (pp) and mixture-of-experts (ep) coverage.

Pipeline: GPipe schedule as a shard_map'd lax.scan with ppermute handoffs
(client_tpu/parallel/pipeline.py).  MoE: top-k routed experts with the
expert dim sharded over the mesh's ``ep`` axis (parallel.param_specs).
Both are validated numerically against the plain single-device forward.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from client_tpu.parallel import (
    batch_spec,
    make_mesh,
    named_shardings,
    param_specs,
)
from client_tpu.parallel.pipeline import stack_stage_params
from client_tpu.serve.models import transformer as tfm

CFG = tfm.TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=4,
    n_heads=4,
    n_kv_heads=2,
    d_ff=64,
    max_seq=32,
    dtype="float32",
)

MOE_CFG = tfm.TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=64,
    max_seq=32,
    dtype="float32",
    n_experts=4,
    top_k=2,
)


def test_make_mesh_five_axes():
    mesh = make_mesh(dp=2, tp=2, pp=2)
    assert mesh.axis_names == ("dp", "tp", "sp", "ep", "pp")
    assert mesh.shape["pp"] == 2 and mesh.shape["ep"] == 1
    with pytest.raises(ValueError):
        make_mesh(dp=2, tp=2, sp=2, pp=2)  # 16 != 8 devices


def test_stack_stage_params_shapes():
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    stages = stack_stage_params(params["layers"], 2)
    assert stages["attn"]["wq"].shape == (2, 2) + params["layers"][0]["attn"]["wq"].shape
    with pytest.raises(ValueError):
        stack_stage_params(params["layers"], 3)  # 4 layers % 3 stages


def test_pipeline_forward_matches_plain():
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, CFG.vocab_size)
    plain = np.asarray(tfm.forward(params, tokens, CFG))
    mesh = make_mesh(dp=2, tp=2, pp=2)
    pparams = tfm.stack_pipeline_params(params, 2)
    piped = np.asarray(
        tfm.forward_pipelined(pparams, tokens, CFG, mesh, n_microbatches=2)
    )
    np.testing.assert_allclose(piped, plain, atol=1e-4, rtol=1e-3)


def test_pipeline_train_step_reduces_loss():
    """Gradients flow back through the scan + ppermute schedule."""
    mesh = make_mesh(dp=2, tp=2, pp=2)
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    pparams = tfm.stack_pipeline_params(params, 2)
    opt, step = tfm.make_pipeline_train_step(
        CFG, mesh, n_microbatches=2, learning_rate=1e-2
    )
    opt_state = opt.init(pparams)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 9), 0, CFG.vocab_size)
    first = None
    for _ in range(5):
        pparams, opt_state, loss = step(pparams, opt_state, tokens)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_moe_forward_and_sharded_ep():
    params = tfm.init_params(jax.random.PRNGKey(2), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, MOE_CFG.vocab_size)
    plain = np.asarray(tfm.forward(params, tokens, MOE_CFG))
    assert np.isfinite(plain).all()
    mesh = make_mesh(dp=2, tp=2, ep=2)
    sp = jax.device_put(params, named_shardings(mesh, param_specs(MOE_CFG)))
    st = jax.device_put(tokens, jax.sharding.NamedSharding(mesh, batch_spec()))
    sharded = np.asarray(tfm.forward(sp, st, MOE_CFG, mesh=mesh))
    np.testing.assert_allclose(sharded, plain, atol=1e-4, rtol=1e-3)


def test_moe_prefill_decode_matches_forward():
    params = tfm.init_params(jax.random.PRNGKey(2), MOE_CFG)
    toks = jax.random.randint(jax.random.PRNGKey(5), (1, 10), 0, MOE_CFG.vocab_size)
    full = np.asarray(tfm.forward(params, toks, MOE_CFG))
    cache = tfm.init_cache(MOE_CFG, 1)
    logits, cache = tfm.prefill(params, toks[:, :6], MOE_CFG, cache)
    np.testing.assert_allclose(np.asarray(logits), full[:, 5], atol=2e-4, rtol=1e-3)
    for i in range(6, 10):
        logits, cache = tfm.decode_step(params, toks[:, i], MOE_CFG, cache)
        np.testing.assert_allclose(
            np.asarray(logits), full[:, i], atol=2e-4, rtol=1e-3
        )


def test_moe_router_aux_loss_in_loss_fn():
    """loss_fn adds the Switch load-balance term for MoE configs."""
    params = tfm.init_params(jax.random.PRNGKey(2), MOE_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(9), (2, 9), 0, MOE_CFG.vocab_size)
    _, aux = tfm.forward(params, tokens[:, :-1], MOE_CFG, with_aux=True)
    aux = float(aux)
    assert np.isfinite(aux) and aux > 0
    base = float(tfm.loss_fn(params, tokens, MOE_CFG))
    no_aux_cfg = tfm.TransformerConfig(
        **{**MOE_CFG.__dict__, "router_aux_coef": 0.0}
    )
    no_aux = float(tfm.loss_fn(params, tokens, no_aux_cfg))
    np.testing.assert_allclose(base - no_aux, MOE_CFG.router_aux_coef * aux,
                               rtol=1e-4, atol=1e-6)


def test_moe_ep_train_step_runs():
    """dp/ep-sharded MoE Adam step on the 8-device mesh."""
    mesh = make_mesh(dp=2, tp=2, ep=2)
    params = tfm.init_params(jax.random.PRNGKey(7), MOE_CFG)
    opt, step = tfm.make_train_step(MOE_CFG, mesh=mesh)
    params = jax.device_put(params, named_shardings(mesh, param_specs(MOE_CFG)))
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(8), (4, 17), 0, MOE_CFG.vocab_size)
    tokens = jax.device_put(
        tokens, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("dp", None))
    )
    params, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
