"""Multi-tenant front door (serve/frontdoor.py + the batcher's weighted-fair
queue): response cache, in-flight coalescing, per-tenant QoS — unit layers
plus the noisy-neighbor / hot-key chaos acceptance:

    a flooding tenant hammers a server shared with 3 compliant tenants
    while a hot-key storm hits one model.  Victims' p99 stays bounded
    (<= 2x their solo baseline), the flooder is shed with 429 +
    Retry-After that the client RetryPolicy absorbs without surfacing
    errors, identical concurrent requests provably dispatch ONCE (from
    server traces AND the model's own execution count), and the
    per-tenant + cache metrics reconcile with observed request counts.
"""

import threading
import time

import numpy as np
import pytest

import client_tpu.http as httpclient
from client_tpu.resilience import RetryPolicy
from client_tpu.serve import Model, Server, TensorSpec
from client_tpu.serve.dynamic_batcher import _FairQueue, _Pending
from client_tpu.serve.frontdoor import (
    Coalescer,
    ResponseCache,
    TenantQoS,
    request_digest,
)
from client_tpu.serve.metrics import Registry, render_metrics
from client_tpu.serve.model_runtime import InferenceEngine
from client_tpu.testing.chaos import ChaosScenario, run_scenario
from client_tpu.utils import InferenceServerException, to_wire_bytes


# -- request digest ----------------------------------------------------------


def _req(value, req_id="", extra_params=None):
    arr = np.full((1, 4), value, dtype=np.float32)
    raw = to_wire_bytes(arr, "FP32")
    req = {
        "id": req_id,
        "parameters": dict(extra_params or {}),
        "inputs": [
            {
                "name": "IN",
                "datatype": "FP32",
                "shape": [1, 4],
                "parameters": {"binary_data_size": len(raw)},
            }
        ],
        "outputs": [{"name": "OUT", "parameters": {"binary_data": True}}],
    }
    return req, raw


class TestRequestDigest:
    def test_identical_content_shares_digest_id_excluded(self):
        a, raw_a = _req(1.0, req_id="client-1")
        b, raw_b = _req(1.0, req_id="client-2")
        assert request_digest("m", "1", a, raw_a) == request_digest(
            "m", "1", b, raw_b
        )

    def test_different_content_differs(self):
        a, raw_a = _req(1.0)
        b, raw_b = _req(2.0)
        assert request_digest("m", "", a, raw_a) != request_digest(
            "m", "", b, raw_b
        )
        # model identity is content
        assert request_digest("m", "", a, raw_a) != request_digest(
            "other", "", a, raw_a
        )
        # request parameters are content (they change rendering/behavior)
        c, raw_c = _req(1.0, extra_params={"binary_data_output": True})
        assert request_digest("m", "", a, raw_a) != request_digest(
            "m", "", c, raw_c
        )

    def test_uncacheable_shapes(self):
        seq, raw = _req(1.0, extra_params={"sequence_id": 7})
        assert request_digest("m", "", seq, raw) is None
        shm_in, raw2 = _req(1.0)
        shm_in["inputs"][0]["parameters"] = {
            "shared_memory_region": "r", "shared_memory_byte_size": 16,
        }
        assert request_digest("m", "", shm_in, b"") is None
        shm_out, raw3 = _req(1.0)
        shm_out["outputs"][0]["parameters"] = {
            "shared_memory_region": "r", "shared_memory_byte_size": 16,
        }
        assert request_digest("m", "", shm_out, raw3) is None


# -- response cache ----------------------------------------------------------


class TestResponseCache:
    def test_lru_eviction_by_entries(self):
        cache = ResponseCache(max_entries=2, registry=Registry())
        cache.put("a", {"outputs": []}, [b"a"])
        cache.put("b", {"outputs": []}, [b"b"])
        assert cache.get("a") is not None  # refresh a
        cache.put("c", {"outputs": []}, [b"c"])  # evicts b (LRU)
        assert cache.get("b") is None
        assert cache.get("a") is not None
        assert cache.get("c") is not None
        stats = cache.stats()
        assert stats["evictions"] == 1 and stats["entries"] == 2

    def test_byte_bound_and_oversize_value(self):
        cache = ResponseCache(max_entries=100, max_bytes=3000)
        cache.put("big", {"outputs": []}, [b"x" * 4000])  # alone > bound
        assert cache.get("big") is None
        cache.put("a", {"outputs": []}, [b"x" * 1500])
        cache.put("b", {"outputs": []}, [b"y" * 1500])  # evicts a by bytes
        assert cache.get("a") is None
        assert cache.get("b") is not None

    def test_ttl_expiry(self):
        cache = ResponseCache(max_entries=4, ttl_s=0.05)
        cache.put("k", {"outputs": []}, [b"v"])
        assert cache.get("k") is not None
        time.sleep(0.08)
        assert cache.get("k") is None  # expired at read time
        assert cache.stats()["evictions"] == 1

    def test_per_entry_ttl_overrides_default(self):
        """The per-model ttl_s hint: an entry carrying its own TTL
        expires on that clock while default-TTL neighbors live on."""
        cache = ResponseCache(max_entries=4, ttl_s=30.0)
        cache.put("fresh", {"outputs": []}, [b"v"], ttl_s=0.05)
        cache.put("stable", {"outputs": []}, [b"v"])
        time.sleep(0.08)
        assert cache.get("fresh") is None  # model's own bound expired it
        assert cache.get("stable") is not None  # cache-wide 30s still good

    def test_metrics_series(self):
        registry = Registry()
        cache = ResponseCache(max_entries=1, registry=registry)
        cache.put("a", {"outputs": []}, [b"a"])
        cache.get("a")
        cache.get("missing")
        cache.put("b", {"outputs": []}, [b"b"])  # evicts a
        assert registry.get("ctpu_cache_hits_total") == 1
        assert registry.get("ctpu_cache_misses_total") == 1
        assert registry.get(
            "ctpu_cache_evictions_total", {"reason": "lru"}
        ) == 1
        assert registry.get("ctpu_cache_entries") == 1


# -- coalescer ---------------------------------------------------------------


class TestCoalescer:
    def test_leader_publishes_to_followers(self):
        c = Coalescer(registry=Registry())
        is_leader, flight = c.join("k")
        assert is_leader
        results = []

        def follow():
            lead, f = c.join("k")
            assert not lead
            f.event.wait(timeout=10)
            results.append(f.result)

        threads = [threading.Thread(target=follow) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.05)  # let the followers join the in-flight key
        c.publish("k", flight, ("resp", []))
        for t in threads:
            t.join(timeout=10)
        assert results == [("resp", [])] * 3
        assert c.coalesced == 3 and c.depth_max == 4
        # the key is released: the next join leads again
        assert c.join("k")[0]

    def test_leader_failure_fans_out(self):
        c = Coalescer()
        _, flight = c.join("k")
        _, f2 = c.join("k")
        err = InferenceServerException("boom", status="500")
        c.fail("k", flight, err)
        assert f2.event.wait(timeout=10) and f2.error is err

    def test_retry_followers_releases_without_error(self):
        c = Coalescer()
        _, flight = c.join("k")
        _, f2 = c.join("k")
        c.retry_followers("k", flight)
        assert f2.event.wait(timeout=10)
        assert f2.retry and f2.error is None
        # the key is free: a re-contending follower leads the next flight
        assert c.join("k")[0]


def test_nontuple_leader_result_never_strands_followers():
    """Hot-swap TOCTOU: if the model is swapped to a decoupled shape
    between the front-key check and execution, the leader's result is a
    stream, not a (response, blobs) tuple.  The flight must still be
    completed (followers re-contend) — an incomplete flight would strand
    every follower on an untimed wait."""
    def fn(inputs, params, ctx):
        return {"OUT": inputs["IN"] * 2.0}

    model = Model(
        "echo",
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
    )
    engine = InferenceEngine(models=[model], coalescing=True)
    follower_joined = threading.Event()
    real_dispatch = engine._front_dispatch
    calls = [0]

    class _FakeStream:
        pass

    def swapped_dispatch(*args, **kwargs):
        calls[0] += 1
        if calls[0] == 1:
            # first (leader) dispatch: simulate the swapped-model shape,
            # holding until the follower is coalesced behind us
            assert follower_joined.wait(timeout=30)
            return _FakeStream()
        return real_dispatch(*args, **kwargs)

    engine._front_dispatch = swapped_dispatch
    try:
        req, raw = _req(3.0)
        leader_result, follower_result, errors = [], [], []

        def leader():
            try:
                leader_result.append(engine.execute("echo", "", dict(req), raw))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def follower():
            deadline = time.monotonic() + 30
            while not engine._coalescer._flights:
                assert time.monotonic() < deadline
                time.sleep(0.001)
            threading.Timer(0.05, follower_joined.set).start()
            try:
                follower_result.append(
                    engine.execute("echo", "", dict(req), raw)
                )
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t1 = threading.Thread(target=leader)
        t2 = threading.Thread(target=follower)
        t1.start()
        t2.start()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert not t2.is_alive(), "follower stranded on the flight"
        assert not errors, errors
        # leader got the (fake) stream through untouched; the follower
        # re-contended and executed for real
        assert isinstance(leader_result[0], _FakeStream)
        assert isinstance(follower_result[0], tuple)
    finally:
        engine.close()


def test_follower_recontends_when_leader_dies_with_replica():
    """Replica-failover regression: a coalesced follower whose leader
    dies WITH its transport (connection-level error — the replica/peer
    the leader was talking to is gone) must re-contend on a surviving
    path, not surface the leader's connection error.  A connection
    error, like a 429, says nothing about the request CONTENT — only a
    content-scoped failure may fan out to the herd."""
    def fn(inputs, params, ctx):
        return {"OUT": inputs["IN"] * 2.0}

    model = Model(
        "echo",
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
    )
    engine = InferenceEngine(models=[model], coalescing=True)
    follower_joined = threading.Event()
    real_dispatch = engine._front_dispatch
    calls = [0]

    def dying_dispatch(*args, **kwargs):
        calls[0] += 1
        if calls[0] == 1:
            # leader's replica dies mid-dispatch, AFTER the follower is
            # coalesced behind it
            assert follower_joined.wait(timeout=30)
            raise InferenceServerException(
                "connection reset by replica",
                debug_details=ConnectionResetError("peer died"),
            )
        return real_dispatch(*args, **kwargs)

    engine._front_dispatch = dying_dispatch
    try:
        req, raw = _req(11.0)
        leader_err, follower_result, follower_err = [], [], []

        def leader():
            try:
                engine.execute("echo", "", dict(req), raw)
            except InferenceServerException as e:
                leader_err.append(e)

        def follower():
            deadline = time.monotonic() + 30
            while not engine._coalescer._flights:
                assert time.monotonic() < deadline
                time.sleep(0.001)
            threading.Timer(0.05, follower_joined.set).start()
            try:
                follower_result.append(
                    engine.execute("echo", "", dict(req), raw)
                )
            except InferenceServerException as e:
                follower_err.append(e)

        t1 = threading.Thread(target=leader)
        t2 = threading.Thread(target=follower)
        t1.start()
        t2.start()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert not t2.is_alive(), "follower stranded on the flight"
        # the leader surfaces ITS error; the follower re-contended as the
        # next leader and executed successfully (two dispatches total)
        assert len(leader_err) == 1
        assert "connection reset" in str(leader_err[0])
        assert not follower_err, follower_err
        assert len(follower_result) == 1 and calls[0] == 2
    finally:
        engine.close()


def test_leader_qos_shed_does_not_poison_other_tenants():
    """A coalesce leader rejected by ITS OWN tenant's quota (429) must not
    fan that tenant-scoped error out to a compliant tenant's identical
    request — the follower re-contends, becomes the new leader under its
    own (unexhausted) quota, and succeeds."""
    calls = []

    def fn(inputs, params, ctx):
        calls.append(1)
        return {"OUT": inputs["IN"] * 2.0}

    model = Model(
        "echo",
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
    )
    follower_joined = threading.Event()

    class _GatedQoS(TenantQoS):
        # the flooder's admission blocks until the compliant follower has
        # joined the flight, then sheds — deterministically recreating
        # "compliant request coalesced behind a shed leader"
        def admit(self, tenant):
            if tenant == "flood":
                assert follower_joined.wait(timeout=30)
            return super().admit(tenant)

    qos = _GatedQoS(tenants={"flood": {"rate_per_s": 0.001, "burst": 0.0}})
    engine = InferenceEngine(models=[model], coalescing=True, qos=qos)
    try:
        req, raw = _req(7.0)
        flood_err, nice_result, nice_err = [], [], []

        def flooder():
            try:
                engine.execute("echo", "", dict(req), raw, tenant="flood")
            except InferenceServerException as e:
                flood_err.append(e)

        def nice():
            # wait until the flooder owns the flight (it is parked in
            # admit), then join as a follower and unblock it
            deadline = time.monotonic() + 30
            while not engine._coalescer._flights:
                assert time.monotonic() < deadline
                time.sleep(0.001)
            threading.Timer(0.05, follower_joined.set).start()
            try:
                nice_result.append(
                    engine.execute("echo", "", dict(req), raw, tenant="ok")
                )
            except InferenceServerException as e:
                nice_err.append(e)

        t1 = threading.Thread(target=flooder)
        t2 = threading.Thread(target=nice)
        t1.start()
        t2.start()
        t1.join(timeout=60)
        t2.join(timeout=60)
        # the flooder got ITS 429; the compliant tenant got a real answer
        assert len(flood_err) == 1 and flood_err[0].status() == "429"
        assert not nice_err, nice_err
        assert len(nice_result) == 1 and len(calls) == 1
    finally:
        engine.close()


# -- per-model cache hints (response_cache config block) ---------------------


def _hint_model(name, calls, response_cache=None):
    from client_tpu.serve.model_runtime import Model, TensorSpec

    def fn(inputs, params, ctx):
        calls.append(name)
        return {"OUT": inputs["IN"] * 2.0}

    return Model(
        name,
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
        response_cache=response_cache,
    )


def _hint_req(value=1.0):
    req, raw = _req(value)
    return dict(req), raw


def test_model_opt_out_skips_cache_but_default_models_cache():
    """The all-models-alike behavior is gone: a model whose config block
    says cacheable=False executes every identical request, while its
    default-config neighbor answers repeats from the cache."""
    calls = []
    engine = InferenceEngine(
        models=[
            _hint_model("uncached", calls,
                        response_cache={"cacheable": False}),
            _hint_model("cached", calls),
        ],
        response_cache=ResponseCache(max_entries=16),
    )
    try:
        req, raw = _hint_req()
        for _ in range(3):
            engine.execute("uncached", "", dict(req), raw)
        assert calls.count("uncached") == 3  # opted out: always executes
        for _ in range(3):
            engine.execute("cached", "", dict(req), raw)
        assert calls.count("cached") == 1  # repeats served from cache
        # the opt-out renders in the model's config for clients to read
        cfg = engine.get_model("uncached").config()
        assert cfg["response_cache"] == {"enable": False}
    finally:
        engine.close()


def test_model_ttl_hint_expires_its_own_entries():
    calls = []
    engine = InferenceEngine(
        models=[_hint_model("fast_stale", calls,
                            response_cache={"cacheable": True,
                                            "ttl_s": 0.05})],
        response_cache=ResponseCache(max_entries=16),  # no default TTL
    )
    try:
        req, raw = _hint_req()
        engine.execute("fast_stale", "", dict(req), raw)
        engine.execute("fast_stale", "", dict(req), raw)
        assert calls.count("fast_stale") == 1  # within the model's TTL
        time.sleep(0.08)
        engine.execute("fast_stale", "", dict(req), raw)
        assert calls.count("fast_stale") == 2  # model's TTL expired it
    finally:
        engine.close()


def test_uncacheable_model_still_coalesces():
    """Opting out of the response cache must not opt out of coalescing:
    N identical CONCURRENT requests to an uncacheable model still
    collapse to one dispatch."""
    calls = []
    release = threading.Event()

    from client_tpu.serve.model_runtime import Model, TensorSpec

    def fn(inputs, params, ctx):
        calls.append(1)
        release.wait(timeout=30)
        return {"OUT": inputs["IN"] * 2.0}

    model = Model(
        "slow_uncached",
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
        response_cache={"cacheable": False},
    )
    engine = InferenceEngine(
        models=[model],
        response_cache=ResponseCache(max_entries=16),
        coalescing=True,
    )
    try:
        req, raw = _hint_req()
        results, errors = [], []

        def call():
            try:
                results.append(
                    engine.execute("slow_uncached", "", dict(req), raw)
                )
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=call) for _ in range(4)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while not calls and time.monotonic() < deadline:
            time.sleep(0.005)
        time.sleep(0.05)  # let the followers pile onto the flight
        release.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        assert not errors, errors
        assert len(results) == 4
        assert len(calls) == 1  # one dispatch for the whole herd
        # and a SUBSEQUENT identical request re-executes: nothing cached
        engine.execute("slow_uncached", "", dict(req), raw)
        assert len(calls) == 2
    finally:
        release.set()
        engine.close()


def test_lm_prefix_knobs_ride_the_model_config():
    """The same config block carries the LM prefix-cache knobs: an
    lm_streaming_batched model built with prefix_cache disabled runs its
    engine cache-less, and the block renders in config()."""
    from client_tpu.serve.models import transformer as tfm
    from client_tpu.serve.models.language import (
        _LmRunner,
        lm_streaming_batched_model,
    )

    cfg = tfm.TransformerConfig(
        vocab_size=258, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=64, dtype="float32",
    )
    model = lm_streaming_batched_model(
        name="lm_hinted", runner=_LmRunner(cfg=cfg),
        response_cache={"prefix_cache": {"enable": False,
                                         "min_prefix_blocks": 2}},
    )
    try:
        sched = model.closer.__self__  # the engine behind close()
        assert sched._prefix_enabled is False
        assert sched.min_prefix_blocks == 2
        assert model.config()["response_cache"]["prefix_cache"] == {
            "enable": False, "min_prefix_blocks": 2,
        }
    finally:
        model.closer()


# -- tenant QoS --------------------------------------------------------------


class TestTenantQoS:
    def test_inflight_cap_with_retry_after(self):
        qos = TenantQoS(tenants={"t": {"max_inflight": 1}})
        release = qos.admit("t")
        with pytest.raises(InferenceServerException) as e:
            qos.admit("t")
        assert e.value.status() == "429"
        assert e.value.retry_after_s > 0
        release()
        release()  # idempotent
        qos.admit("t")()  # slot free again
        snap = qos.snapshot()["t"]
        assert snap["shed"] == 1 and snap["inflight"] == 0

    def test_token_bucket_quota(self):
        qos = TenantQoS(
            tenants={"t": {"rate_per_s": 10.0, "burst": 2.0}}
        )
        qos.admit("t")()
        qos.admit("t")()  # burst exhausted
        with pytest.raises(InferenceServerException) as e:
            qos.admit("t")
        assert e.value.status() == "429"
        # the hint says when a token will exist (~1/rate seconds)
        assert 0 < e.value.retry_after_s <= 0.2
        time.sleep(0.12)  # one token refills at 10/s
        qos.admit("t")()

    def test_weights_and_default(self):
        qos = TenantQoS(
            default_weight=1.0,
            tenants={"gold": {"weight": 8.0}, "zero": {"weight": 0.0}},
        )
        assert qos.weight("gold") == 8.0
        assert qos.weight("anyone") == 1.0
        assert qos.weight("zero") > 0  # floored: never full starvation

    def test_priority_classes(self):
        """Preemption priority: per-tenant `priority` key, default 0 —
        the LM engine's swap controller only acts on STRICT inequality,
        so unconfigured fleets never preempt."""
        qos = TenantQoS(tenants={"gold": {"priority": 10}})
        assert qos.priority("gold") == 10.0
        assert qos.priority("anyone") == 0.0
        assert TenantQoS(default_priority=2.5).priority("x") == 2.5

    def test_note_counts_without_caps(self):
        registry = Registry()
        qos = TenantQoS(
            tenants={"t": {"max_inflight": 1}}, registry=registry
        )
        hold = qos.admit("t")
        qos.note("t")  # cache-hit path: counted, never shed
        hold()
        assert registry.get(
            "ctpu_tenant_requests_total", {"tenant": "t"}
        ) == 2
        assert qos.snapshot()["t"]["shed"] == 0


# -- weighted-fair queue -----------------------------------------------------


def _pending(tenant, weight=1.0, rows=1):
    return _Pending({}, rows, ("sig",), tenant=tenant, weight=weight)


class TestFairQueue:
    def test_flooder_backlog_does_not_block_late_arrival(self):
        q = _FairQueue()
        for _ in range(10):
            q.push(_pending("flood"))
        q.push(_pending("nice"))  # arrives AFTER the whole backlog
        order = [q.pop().tenant for _ in range(4)]
        # fair interleave: nice is served 2nd, not 11th (FIFO would)
        assert order[1] == "nice", order

    def test_weight_ratio_governs_service(self):
        q = _FairQueue()
        for _ in range(20):
            q.push(_pending("gold", weight=4.0))
            q.push(_pending("bronze", weight=1.0))
        first = [q.pop().tenant for _ in range(10)]
        assert first.count("gold") >= 7, first  # ~4:1 service ratio

    def test_lane_order_stays_fifo_and_take_first(self):
        q = _FairQueue()
        a1, a2 = _pending("a"), _pending("a")
        q.push(a1)
        q.push(a2)
        assert q.pop() is a1  # FIFO within a lane
        taken = q.take_first(lambda p: p.tenant == "a")
        assert taken is a2 and len(q) == 0
        assert q.take_first(lambda p: True) is None

    def test_depths_and_drain(self):
        q = _FairQueue()
        q.push(_pending("a"))
        q.push(_pending("a"))
        q.push(_pending("b"))
        assert q.depths() == {"a": 2, "b": 1}
        assert len(q.drain()) == 3
        assert len(q) == 0 and q.depths() == {}


# -- batched path: per-tenant lanes reach the batcher ------------------------


def test_batcher_fair_queue_integration():
    """Tenanted requests flow into per-tenant batcher lanes; the per-tenant
    queue-depth gauge and weighted service both come from the same
    _FairQueue the engine feeds through submit(tenant=, weight=)."""
    record = []

    def fn(inputs, params, ctx):
        record.append(int(inputs["IN"].shape[0]))
        time.sleep(0.002)
        return {"OUT": inputs["IN"] * 2.0}

    model = Model(
        "echo2x",
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
        max_batch_size=8,
        dynamic_batching=True,
        max_queue_delay_us=5000,
    )
    qos = TenantQoS(tenants={"gold": {"weight": 4.0}})
    engine = InferenceEngine(models=[model], qos=qos)
    try:
        n = 12
        barrier = threading.Barrier(n)
        errors = []

        def run(i, tenant):
            req, raw = _req(float(i))
            try:
                barrier.wait()
                engine.execute("echo2x", "", req, raw, tenant=tenant)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(
                target=run, args=(i, "gold" if i % 2 else "bronze")
            )
            for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        assert sum(record) >= n  # all rows served (padding included)
        stats = engine.statistics("echo2x")[0]["inference_stats"]
        assert stats["success"]["count"] == n
    finally:
        engine.close()


# -- chaos acceptance --------------------------------------------------------


VALUE_SPACE = 10_000  # compliant tenants draw unique values: no cache hits


def _work_model(calls, delay_s=0.004):
    """Fixed-cost model recording every execution's input marker."""

    def fn(inputs, params, ctx):
        calls.append(float(np.asarray(inputs["IN"]).flatten()[0]))
        time.sleep(delay_s)
        return {"OUT": inputs["IN"] * 2.0}

    return Model(
        "work",
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
    )


def _infer(client, value, tenant, headers_extra=None):
    inp = httpclient.InferInput("IN", [1, 4], "FP32")
    inp.set_data_from_numpy(np.full((1, 4), value, dtype=np.float32))
    headers = {"x-tenant-id": tenant}
    headers.update(headers_extra or {})
    return client.infer("work", [inp], headers=headers)


def _compliant_run(addr, tenant, n, out_latencies, out_errors, base):
    client = httpclient.InferenceServerClient(addr)
    try:
        for i in range(n):
            value = float(base + i)  # unique content: always executes
            t0 = time.monotonic()
            try:
                _infer(client, value, tenant)
                out_latencies.append(time.monotonic() - t0)
            except Exception as e:  # noqa: BLE001
                out_errors.append(e)
    finally:
        client.close()


def _p99(samples):
    return float(np.percentile(np.asarray(samples), 99))


def _run_noisy_neighbor(n_per_tenant, flood_threads, storm_n, delay_s):
    calls = []
    qos = TenantQoS(
        # compliant tenants are unmetered; the flooder's caps are what a
        # real deployment would provision for an untrusted integration
        tenants={"flood": {"max_inflight": 2, "weight": 0.5}},
    )
    server = Server(
        models=[_work_model(calls, delay_s)],
        with_default_models=False,
        max_inflight=32,
        response_cache=ResponseCache(max_entries=256),
        coalescing=True,
        qos=qos,
    ).start()
    engine = server.engine
    engine.update_trace_settings(
        {"trace_level": ["TIMESTAMPS"], "trace_rate": "1"}
    )
    addr = server.http_address
    tenants = ["alice", "bob", "carol"]
    def _compliant_driver(tenant, out, base):
        # raising variant of _compliant_run: the chaos harness collects
        # driver exceptions and assert_clean() is the zero-error gate
        errs = []
        _compliant_run(addr, tenant, n_per_tenant, out, errs, base)
        assert not errs, errs

    try:
        # -- phase 1: solo baselines (chaos harness drives the threads,
        # collects errors, detects wedged drivers) ----------------------
        solo = {t: [] for t in tenants}
        run_scenario(
            ChaosScenario("noisy-neighbor-solo"), lambda fault: None,
            [
                (lambda t=t, i=i: _compliant_driver(t, solo[t], 1000 * i))
                for i, t in enumerate(tenants)
            ],
            join_timeout_s=120,
        ).assert_clean()

        # -- phase 2: flooder + hot-key storm + compliant tenants -------
        stop_flood = threading.Event()
        flood_errors = []
        flood_ok = [0]
        flood_policy = RetryPolicy(
            max_attempts=8, initial_backoff_s=0.02, max_backoff_s=0.3,
        )

        def flooder():
            client = httpclient.InferenceServerClient(
                addr, retry_policy=flood_policy
            )
            try:
                i = 0
                while not stop_flood.is_set():
                    i += 1
                    try:
                        # unique content: no cache help for the flooder
                        _infer(client, 50_000 + hash((id(client), i)) % 50_000,
                               "flood")
                        flood_ok[0] += 1
                    except Exception as e:  # noqa: BLE001
                        flood_errors.append(e)
                    # a flooding INTEGRATION still runs over real sockets
                    # with nonzero think time; a zero-delay spin here mostly
                    # measures the test harness's own GIL contention
                    time.sleep(0.002)
            finally:
                client.close()

        flooders = [
            threading.Thread(target=flooder) for _ in range(flood_threads)
        ]
        for t in flooders:
            t.start()

        # hot-key storm: identical concurrent requests on one value
        storm_barrier = threading.Barrier(storm_n)
        hot_value = 99_999.0

        def storm():
            client = httpclient.InferenceServerClient(addr)
            try:
                storm_barrier.wait(timeout=60)
                _infer(client, hot_value, "alice")
            finally:
                client.close()

        # compliant tenants + the storm ride the chaos harness as one
        # driver set (one scenario, one zero-error/zero-wedge gate); the
        # flooders stay background load, stopped after the run
        attack = {t: [] for t in tenants}
        attack_result = run_scenario(
            ChaosScenario("noisy-neighbor-attack"), lambda fault: None,
            [
                (lambda t=t, i=i: _compliant_driver(
                    t, attack[t], 10_000 + 1000 * i,
                ))
                for i, t in enumerate(tenants)
            ] + [storm] * storm_n,
            join_timeout_s=180,
        )
        stop_flood.set()
        for t in flooders:
            t.join(timeout=60)

        # -- acceptance: zero errors for compliant tenants + storm ------
        attack_result.assert_clean()
        # flooder rejections were absorbed by its RetryPolicy: its
        # requests slowed down but did not ERROR
        assert not flood_errors, flood_errors[:3]
        assert flood_ok[0] > 0  # the flooder still made progress

        # -- acceptance: victims' p99 stays bounded ---------------------
        for t in tenants:
            solo_p99 = _p99(solo[t])
            attack_p99 = _p99(attack[t])
            # 2x the solo baseline, plus a small absolute grace so a
            # microsecond-scale baseline cannot fail on scheduler jitter
            assert attack_p99 <= 2.0 * solo_p99 + 0.05, (
                "p99-bound", t, solo_p99, attack_p99,
            )

        # -- acceptance: the flooder was shed with Retry-After ----------
        raw_client = httpclient.InferenceServerClient(addr)  # no retries
        sheds = 0
        retry_after_seen = None
        for i in range(40):
            try:
                _infer(raw_client, 200_000 + i, "flood")
            except InferenceServerException as e:
                assert e.status() == "429"
                retry_after_seen = getattr(e, "retry_after_s", None)
                sheds += 1
        raw_client.close()
        metrics_client = httpclient.InferenceServerClient(addr)
        text = render_metrics(engine)
        metrics_client.close()
        shed_total = sum(
            engine.qos.snapshot().get("flood", {}).get("shed", 0)
            for _ in (0,)
        )
        if sheds:  # the raw burst outran the caps (expected)
            assert retry_after_seen is not None and retry_after_seen > 0
        assert shed_total > 0, "the flooder was never shed"
        assert 'ctpu_tenant_shed_total{reason="inflight",tenant="flood"}' \
            in text or 'ctpu_tenant_shed_total{reason="quota",tenant="flood"}' \
            in text

        # -- acceptance: hot key dispatched exactly once ----------------
        assert calls.count(hot_value) == 1, calls.count(hot_value)
        hot_spans = [
            tr for tr in engine.tracer.completed
            if tr.model_name == "work"
            and any(
                e["name"] in ("CACHE_HIT", "COALESCED", "COMPUTE_START")
                for e in tr.timestamps
            )
        ]
        storm_spans = [
            tr for tr in engine.tracer.completed if tr.tenant == "alice"
        ]
        assert storm_spans  # tenant tag rides the server spans
        computed = coalesced = cached = 0
        # count across ALL spans how the storm requests were served: the
        # compliant alice worker also traces, so key on the storm's
        # timing shape — every storm span is CACHE_HIT or COALESCED or
        # the one leader; the direct proof is calls.count above, and the
        # trace proof is that SOME spans carry the fast-path events
        for tr in engine.tracer.completed:
            names = {e["name"] for e in tr.timestamps}
            if "CACHE_HIT" in names:
                cached += 1
            elif "COALESCED" in names:
                coalesced += 1
            elif "COMPUTE_START" in names:
                computed += 1
        assert coalesced + cached >= storm_n - 1, (
            coalesced, cached, computed,
        )
        assert len(hot_spans) > 0

        # -- acceptance: metrics reconcile with observed counts ---------
        snap = engine.qos.snapshot()
        for i, t in enumerate(tenants):
            # compliant tenants: exactly their sent requests, no sheds
            sent = 2 * n_per_tenant + (storm_n if t == "alice" else 0)
            assert snap[t]["requests"] == sent, (t, snap[t], sent)
            assert snap[t]["shed"] == 0
        # flooder: every request either executed or was shed, nothing lost
        stats = engine.statistics("work")[0]
        istats = stats["inference_stats"]
        cache_stats = engine.response_cache.stats()
        assert cache_stats["hits"] == istats["cache_hit"]["count"]
        # every successful request is accounted: executions + cache hits
        # + coalesced followers == success_count
        assert istats["success"]["count"] == (
            len(calls) + cache_stats["hits"] + engine._coalescer.coalesced
        )
        return {
            "sheds": shed_total,
            "coalesced": engine._coalescer.coalesced,
            "cache_hits": cache_stats["hits"],
        }
    finally:
        server.stop()


def _chaos_with_p99_retry(attempts=3, **kwargs):
    """Run the scenario, re-measuring when ONLY the p99 timing bound
    misses: on an oversubscribed CI box one ~0.5s scheduler stall in
    either phase skews a percentile computed from tens of samples.
    Correctness invariants (zero errors, exactly-once dispatch, metric
    reconciliation) are never retried — a real bug fails every attempt."""
    last = None
    for _ in range(attempts):
        try:
            return _run_noisy_neighbor(**kwargs)
        except AssertionError as e:
            if "p99-bound" not in str(e):
                raise
            last = e
    raise last


def test_noisy_neighbor_and_hot_key_chaos():
    """The tier-1 acceptance scenario (see module docstring).  The model
    delay is large enough that server-side time dominates the
    measurement — at sub-5ms the client threads' own GIL contention is
    what the p99 would measure."""
    summary = _chaos_with_p99_retry(
        n_per_tenant=30, flood_threads=4, storm_n=8, delay_s=0.015
    )
    assert summary["sheds"] > 0


@pytest.mark.slow
def test_noisy_neighbor_soak():
    """Bigger, longer variant for `make soak` — isolation bugs are timing
    bugs; repetition and scale find them."""
    summary = _chaos_with_p99_retry(
        n_per_tenant=80, flood_threads=8, storm_n=16, delay_s=0.015
    )
    assert summary["sheds"] > 0


def test_chaos_lock_order_witness():
    """The dynamic lock-order witness (client_tpu.analysis.witness) armed
    over the noisy-neighbor chaos scenario: every lock/condition the front
    door, batcher, engine, pool, and clients construct records the REAL
    acquisition DAG this run exercises.  The acceptance is a non-trivial,
    acyclic graph — the runtime complement of the static LOCK-INV rule
    (a cycle only the witness sees is a dynamic aliasing pattern the
    summaries cannot name; one only the static pass sees is an
    unexercised path)."""
    from client_tpu.analysis.witness import LockWitness

    witness = LockWitness()
    with witness.installed():
        summary = _chaos_with_p99_retry(
            n_per_tenant=30, flood_threads=4, storm_n=8, delay_s=0.015
        )
    assert summary["sheds"] > 0
    edges = witness.assert_acyclic()
    # the scenario nests acquisitions (batcher cond -> metrics registry,
    # QoS lock -> registry, cache lock -> registry): an edgeless graph
    # means the witness was not actually armed
    assert edges > 0
    assert witness.acquisitions > 0
