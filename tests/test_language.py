"""Language stack: tokenizer, streaming LM, text ensemble (BASELINE config 5).

Uses a tiny transformer config so CPU tests stay fast; the serving protocol
path (decoupled responses over gRPC ModelStreamInfer) is identical to the
full-size deployment.
"""

import queue

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
from client_tpu.serve import Server
from client_tpu.serve.models import transformer as tfm
from client_tpu.serve.models.language import (
    _LmRunner,
    decode_tokens,
    detokenizer_model,
    encode_text,
    lm_streaming_model,
    text_ensemble_model,
    tokenizer_model,
)

_TINY = tfm.TransformerConfig(
    vocab_size=258, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq=64, dtype="float32",
)


@pytest.fixture(scope="module")
def runner():
    return _LmRunner(cfg=_TINY)


@pytest.fixture(scope="module")
def server(runner):
    models = [
        tokenizer_model(),
        detokenizer_model(),
        lm_streaming_model(runner=runner),
        lm_streaming_model(
            name="lm_streaming_int8",
            runner=_LmRunner(cfg=_TINY, quantize=True),
        ),
        text_ensemble_model(runner=runner),
    ]
    with Server(models=models, grpc_port=0, with_default_models=False) as s:
        yield s


@pytest.fixture()
def client(server):
    with grpcclient.InferenceServerClient(server.grpc_address) as c:
        yield c


def test_encode_decode_round_trip():
    text = "hello, TPU! ünïcödé"
    toks = encode_text(text)
    assert toks[0] == 256  # BOS
    assert decode_tokens(toks) == text


def test_overlong_prompt_is_client_error(client, runner):
    """r1 advisor: a prompt beyond the model's max context must surface as a
    400 InferenceServerException, not an opaque jit shape failure."""
    from client_tpu.utils import InferenceServerException

    too_long = np.arange(runner.cfg.max_seq + 8, dtype=np.int32) % 255
    inp_tok = grpcclient.InferInput("TOKENS", [len(too_long)], "INT32")
    inp_tok.set_data_from_numpy(too_long)
    inp_max = grpcclient.InferInput("MAX_TOKENS", [1], "INT32")
    inp_max.set_data_from_numpy(np.array([4], dtype=np.int32))
    results = queue.Queue()
    client.start_stream(callback=lambda result, error: results.put(error))
    try:
        client.async_stream_infer("lm_streaming", [inp_tok, inp_max])
        err = results.get(timeout=30)
    finally:
        client.stop_stream()
    assert isinstance(err, InferenceServerException)
    assert "maximum context" in str(err)
    assert err.status() in ("400", "INVALID_ARGUMENT")


def test_empty_prompt_is_client_error(runner):
    from client_tpu.utils import InferenceServerException

    with pytest.raises(InferenceServerException, match="empty prompt"):
        list(runner.stream(np.array([], dtype=np.int32), 4))


def test_tokenizer_model_batch(client):
    texts = np.array([b"ab", b"wxyz"], dtype=np.object_)
    inp = grpcclient.InferInput("TEXT", [2], "BYTES")
    inp.set_data_from_numpy(texts)
    res = client.infer("tokenizer", [inp])
    tokens = res.as_numpy("TOKENS")
    lengths = res.as_numpy("LENGTHS")
    assert list(lengths) == [3, 5]
    assert tokens.shape == (2, 5)
    assert decode_tokens(tokens[1][: lengths[1]]) == "wxyz"


def test_detokenizer_model(client):
    toks = encode_text("roundtrip")[None, :]
    inp = grpcclient.InferInput("TOKENS", list(toks.shape), "INT32")
    inp.set_data_from_numpy(toks.astype(np.int32))
    res = client.infer("detokenizer", [inp])
    assert res.as_numpy("TEXT")[0] == b"roundtrip"


@pytest.mark.parametrize("model_name", ["lm_streaming", "lm_streaming_int8"])
def test_lm_streaming_over_grpc(client, model_name):
    """One decoupled response per generated token, in order — same protocol
    from the bf16 and the int8-quantized LM."""
    results = queue.Queue()
    client.start_stream(
        callback=lambda result, error: results.put((result, error))
    )
    prompt = encode_text("abc")
    t_in = grpcclient.InferInput("TOKENS", [len(prompt)], "INT32")
    t_in.set_data_from_numpy(prompt)
    m_in = grpcclient.InferInput("MAX_TOKENS", [1], "INT32")
    m_in.set_data_from_numpy(np.array([6], dtype=np.int32))
    client.async_stream_infer(model_name, [t_in, m_in])
    tokens = []
    for _ in range(6):
        result, error = results.get(timeout=30)
        assert error is None
        tokens.append(int(result.as_numpy("TOKEN")[0]))
        if tokens[-1] == 257:  # EOS ends the stream early
            break
    client.stop_stream()
    assert tokens
    assert all(0 <= t < 258 for t in tokens)


def test_decoupled_final_response_protocol(client):
    """Triton's decoupled completion protocol: every streamed response is
    marked triton_final_response=false, and with
    enable_empty_final_response the stream ends with one extra EMPTY
    response marked true — completion detection without model-specific EOS
    logic (reference grpc/__init__.py triton_enable_empty_final_response)."""
    results = queue.Queue()
    client.start_stream(
        callback=lambda result, error: results.put((result, error))
    )
    prompt = encode_text("abc")
    t_in = grpcclient.InferInput("TOKENS", [len(prompt)], "INT32")
    t_in.set_data_from_numpy(prompt)
    m_in = grpcclient.InferInput("MAX_TOKENS", [1], "INT32")
    m_in.set_data_from_numpy(np.array([4], dtype=np.int32))
    client.async_stream_infer(
        "lm_streaming", [t_in, m_in], enable_empty_final_response=True
    )
    seen_final = False
    token_responses = 0
    for _ in range(12):
        result, error = results.get(timeout=30)
        assert error is None
        params = result.get_response().parameters
        is_final = params["triton_final_response"].bool_param
        if is_final:
            # the final marker response is EMPTY
            assert result.as_numpy("TOKEN") is None
            seen_final = True
            break
        assert params["triton_final_response"].bool_param is False
        assert result.as_numpy("TOKEN") is not None
        token_responses += 1
    client.stop_stream()
    assert seen_final
    assert token_responses >= 1


def test_lm_streaming_deterministic(runner):
    a = list(runner.stream(encode_text("abc"), 5))
    b = list(runner.stream(encode_text("abc"), 5))
    assert a == b


def test_text_ensemble_end_to_end(client):
    """BYTES prompt in -> streamed BYTES pieces out (config-5 shape)."""
    results = queue.Queue()
    client.start_stream(
        callback=lambda result, error: results.put((result, error))
    )
    p_in = grpcclient.InferInput("PROMPT", [1], "BYTES")
    p_in.set_data_from_numpy(np.array([b"Once upon"], dtype=np.object_))
    m_in = grpcclient.InferInput("MAX_TOKENS", [1], "INT32")
    m_in.set_data_from_numpy(np.array([4], dtype=np.int32))
    client.async_stream_infer("text_generator", [p_in, m_in])
    pieces = []
    for _ in range(4):
        result, error = results.get(timeout=30)
        assert error is None
        pieces.append(result.as_numpy("TEXT")[0])
        if pieces[-1] == b"":  # EOS piece decodes to empty
            break
    client.stop_stream()
    assert pieces


def test_sampling_temperature_param(runner):
    greedy = list(runner.stream(encode_text("xy"), 5))
    sampled = list(runner.stream(encode_text("xy"), 5, temperature=1.5, seed=7))
    assert len(sampled) >= 1
    # different seeds give different samples (overwhelmingly likely)
    sampled2 = list(runner.stream(encode_text("xy"), 5, temperature=1.5, seed=8))
    assert sampled != sampled2 or sampled != greedy


def test_decoupled_responses_stream_lazily():
    """Each decoupled response must reach the wire as the model produces it:
    time-to-first-response stays far below total stream time (a buffering
    engine would make TTFT equal full generation time — seconds per request
    for LLM token streaming on a remote chip)."""
    import time

    from client_tpu.serve.model_runtime import (
        InferenceEngine,
        Model,
        TensorSpec,
    )

    delay_s = 0.15

    def fn(inputs, params, ctx):
        for i in range(4):
            time.sleep(delay_s)
            yield {"OUT": np.array([i], dtype=np.int32)}

    model = Model(
        "slow_stream",
        inputs=[TensorSpec("IN", "INT32", [1])],
        outputs=[TensorSpec("OUT", "INT32", [1])],
        fn=fn,
        decoupled=True,
    )
    engine = InferenceEngine(models=[model])
    try:
        request = {
            "id": "",
            "parameters": {},
            "inputs": [
                {"name": "IN", "datatype": "INT32", "shape": [1],
                 "data": [4]}
            ],
        }
        t0 = time.perf_counter()
        stream = engine.execute("slow_stream", "", request, b"")
        arrival = []
        values = []
        for response_json, blobs in stream:
            arrival.append(time.perf_counter() - t0)
            values.append(response_json["outputs"][0]["data"][0])
        assert values == [0, 1, 2, 3]
        # first response arrives ~1 delay in; a buffering engine would make
        # it arrive only after all 4 delays
        assert arrival[0] < 2.5 * delay_s, arrival
        assert arrival[-1] >= 3.5 * delay_s, arrival
        # one statistics entry per completed request, recorded at exhaustion
        stats = engine.statistics("slow_stream")[0]["inference_stats"]
        assert stats["success"]["count"] == 1
    finally:
        engine.close()


def test_decoupled_model_response_parameters_survive():
    """A model-set response-level parameter (reserved "__parameters__"
    result key) must survive the decoupled stream: the engine merges its
    triton_final_response marker into the model's parameters instead of
    replacing them (regression: the pre-fix code overwrote the dict)."""
    from client_tpu.serve.model_runtime import (
        InferenceEngine,
        Model,
        TensorSpec,
    )

    def fn(inputs, params, ctx):
        for i in range(3):
            yield {
                "OUT": np.array([i], dtype=np.int32),
                "__parameters__": {"sequence_index": i, "my_flag": True},
            }

    model = Model(
        "param_stream",
        inputs=[TensorSpec("IN", "INT32", [1])],
        outputs=[TensorSpec("OUT", "INT32", [1])],
        fn=fn,
        decoupled=True,
    )
    engine = InferenceEngine(models=[model])
    try:
        request = {
            "id": "",
            "parameters": {},
            "inputs": [
                {"name": "IN", "datatype": "INT32", "shape": [1],
                 "data": [4]}
            ],
        }
        seen = []
        for response_json, _ in engine.execute("param_stream", "", request,
                                               b""):
            seen.append(response_json["parameters"])
        assert [p["sequence_index"] for p in seen] == [0, 1, 2]
        assert all(p["my_flag"] is True for p in seen)
        # the completion-protocol marker is merged in beside them
        assert all(p["triton_final_response"] is False for p in seen)
        # the reserved key is not a requestable output tensor
        from client_tpu.utils import InferenceServerException

        bad = dict(request)
        bad["outputs"] = [{"name": "__parameters__"}]
        with pytest.raises(InferenceServerException):
            for _ in engine.execute("param_stream", "", bad, b""):
                pass
    finally:
        engine.close()
