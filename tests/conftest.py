"""Test-suite configuration.

Tests run on a virtual 8-device CPU mesh so sharding paths compile and execute
without TPU hardware (the driver separately dry-runs the multi-chip path; bench.py
runs on the real chip and does NOT import this).  Must run before jax is imported.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # override axon: tests run hermetic on CPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax before this file runs, so the env
# vars above are too late for jax's import-time config reads — force them
# through the config API (safe while no backend has been initialized yet).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; the slow tier (make soak) repeats the
    # churn chaos scenario to shake out timing bugs
    config.addinivalue_line(
        "markers", "slow: soak/repetition tests excluded from tier-1"
    )


def pytest_addoption(parser):
    parser.addoption(
        "--lock-witness", action="store_true", default=False,
        help=(
            "wrap every lock/condition constructed under client_tpu/ in "
            "the dynamic lock-order witness and fail any test whose "
            "acquisition graph closes a cycle (TPULINT_LOCK_WITNESS=1 "
            "does the same — the make-soak hookup)"
        ),
    )
    parser.addoption(
        "--race-witness", action="store_true", default=False,
        help=(
            "arm the dynamic RACE witness on top of the lock-order one: "
            "@witness_shared classes run the Eraser lockset algorithm "
            "on every field access against the real held-lock stack; an "
            "unguarded shared write fails the test with both stacks "
            "(TPULINT_RACE_WITNESS=1 does the same — the make-chaos/"
            "make-soak hookup)"
        ),
    )
    parser.addoption(
        "--resource-witness", action="store_true", default=False,
        help=(
            "arm the dynamic resource-leak witness: every registered "
            "acquire/release pair (KvBlockPool alloc/release, endpoint "
            "leases, tracer spans) is tracked in a live-handle table "
            "with acquisition stacks, and a test that ends with live "
            "handles fails at its own teardown with the stacks that "
            "acquired them (TPULINT_RESOURCE_WITNESS=1 does the same — "
            "the make-chaos/make-soak hookup)"
        ),
    )


import pytest  # noqa: E402


def _env_truthy(name):
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "no", "off"
    )


@pytest.fixture(autouse=True)
def _lock_order_witness(request):
    """Opt-in dynamic lock-order witness (see client_tpu.analysis.witness):
    records the acquisition DAG the test actually exercises and fails on a
    cycle — the runtime complement of the static LOCK-INV rule.  With
    --race-witness / TPULINT_RACE_WITNESS=1 the witness is a RaceWitness:
    lock-order duty plus runtime Eraser lockset checks on @witness_shared
    classes (the complement of the static LOCKSET-RACE rule), violations
    dumped to the flight recorder."""
    race = request.config.getoption("--race-witness") or _env_truthy(
        "TPULINT_RACE_WITNESS"
    )
    enabled = race or request.config.getoption(
        "--lock-witness"
    ) or _env_truthy("TPULINT_LOCK_WITNESS")
    if not enabled:
        yield None
        return
    if race:
        from client_tpu.analysis.witness import RaceWitness

        flight = None
        if os.environ.get("TPU_FLIGHT_DIR"):
            from client_tpu.serve.flight import FlightRecorder

            flight = FlightRecorder(name="race-witness")
        witness = RaceWitness(flight=flight)
    else:
        from client_tpu.analysis.witness import LockWitness

        witness = LockWitness()
    with witness.installed():
        yield witness
    witness.assert_acyclic()
    if race:
        witness.assert_race_free()


@pytest.fixture(autouse=True)
def _resource_leak_audit(request):
    """Opt-in dynamic resource-leak audit (the runtime complement of the
    static RESOURCE-LEAK rule): with --resource-witness /
    TPULINT_RESOURCE_WITNESS=1 every registered acquire/release pair is
    patched into a live-handle table, and a test that leaks a KV block
    reservation, endpoint lease or tracer span fails at its own teardown
    with the acquisition stacks of the leaked handles.  Leaks are also
    dumped to the flight recorder when TPU_FLIGHT_DIR is set."""
    enabled = request.config.getoption("--resource-witness") or _env_truthy(
        "TPULINT_RESOURCE_WITNESS"
    )
    if not enabled:
        yield None
        return
    from client_tpu.analysis.witness import ResourceWitness

    flight = None
    if os.environ.get("TPU_FLIGHT_DIR"):
        from client_tpu.serve.flight import FlightRecorder

        flight = FlightRecorder(name="resource-witness")
    witness = ResourceWitness(flight=flight)
    with witness.installed():
        yield witness
    witness.assert_clean()


# Native libraries are build artifacts (gitignored): build them on demand so a
# fresh checkout runs the full suite instead of failing the shm-backed tests.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for _lib in (
    "client_tpu/utils/shared_memory/libcshm_tpu.so",
    "client_tpu/utils/tpu_shared_memory/libctpushm.so",
):
    if not os.path.exists(os.path.join(_ROOT, _lib)):
        import subprocess

        subprocess.run(["make", "-C", _ROOT, "native"], check=True,
                       capture_output=True)
        break
