#!/usr/bin/env python
"""Package the client_tpu wheel.

Parity with the reference's Python packaging (reference
src/python/library/setup.py: extras [http]/[grpc]/[all]) — here the
transports ride the standard library + grpcio/urllib3 and the native pieces
are the shm libraries built by `make native` (libcshm_tpu.so, the
libcshm.so analog) which build_wheel.py stages into the package before
bdist_wheel (reference build_wheel.py:165-179 pattern).
"""

import os

from setuptools import find_packages, setup

_HERE = os.path.dirname(os.path.abspath(__file__))


def _version():
    scope = {}
    with open(os.path.join(_HERE, "client_tpu", "_version.py")) as f:
        exec(f.read(), scope)
    return scope.get("__version__", "0.0.0")


setup(
    name="client-tpu",
    version=_version(),
    description=(
        "TPU-native KServe-v2 inference client framework: gRPC/HTTP clients "
        "(sync + asyncio), system and TPU-HBM shared-memory transports, "
        "in-process server, and a perf_analyzer-class load harness"
    ),
    license="BSD-3-Clause",
    packages=find_packages(include=["client_tpu", "client_tpu.*"]),
    package_data={
        "client_tpu.utils.shared_memory": ["libcshm_tpu.so"],
        "client_tpu.analysis": ["baseline.json"],
    },
    python_requires=">=3.9",
    install_requires=["numpy>=1.22", "urllib3>=1.26", "protobuf>=3.19"],
    extras_require={
        "grpc": ["grpcio>=1.41"],
        "tpu": ["jax>=0.4.30"],
        "all": ["grpcio>=1.41", "jax>=0.4.30"],
    },
    entry_points={
        "console_scripts": [
            "client-tpu-perf=client_tpu.perf.__main__:main",
            "client-tpu-serve=client_tpu.serve.__main__:main",
            "client-tpu-lint=client_tpu.analysis.__main__:main",
        ],
    },
)
