"""Transport-agnostic resilience policies: retry, deadline, circuit breaker.

The four clients (``client_tpu.http``, ``client_tpu.http.aio``,
``client_tpu.grpc``, ``client_tpu.grpc.aio``) accept an opt-in
``retry_policy=RetryPolicy(...)`` constructor argument and route every
unary call through :func:`call_with_retry` / :func:`acall_with_retry`.
The server side (``client_tpu.serve``) sheds overload with *retryable*
503/``UNAVAILABLE`` errors, so client retries and server shedding compose:
a shed request backs off and lands once the queue drains.

Design points (the battle-tested shape — AWS architecture blog "Exponential
Backoff And Jitter", gRPC retry design):

- **Exponential backoff with full jitter**: attempt ``k`` sleeps
  ``uniform(0, min(max_backoff, initial * multiplier**k))``.  Full jitter
  decorrelates client herds — a fleet of clients retrying a recovering
  server must not arrive in lockstep waves.
- **Retryable classification**: connection-level failures (refused, reset,
  timed out, truncated) and explicit overload statuses (HTTP 429/503, gRPC
  ``UNAVAILABLE``/``RESOURCE_EXHAUSTED``).  Application errors (bad input,
  unknown model, INTERNAL) never retry — replaying them wastes the budget
  and can double-apply side effects.
- **Retry-After**: a server-provided hint (``exc.retry_after_s``, parsed
  from the HTTP ``Retry-After`` header) overrides the computed backoff,
  capped at ``max_backoff_s`` so a hostile/buggy hint cannot park the
  client.
- **Deadline budget**: one :class:`Deadline` caps the *total* wall time
  across all attempts and derives each attempt's transport timeout from
  what remains — N attempts never multiply the caller's patience by N,
  and a backoff that would outlive the budget short-circuits to the final
  error immediately (no retry storm, no useless terminal sleep).
- **Circuit breaker**: per-endpoint closed → open → half-open.  After
  ``failure_threshold`` consecutive failures the breaker opens and calls
  fail fast (no socket work) for ``reset_timeout_s``; then exactly one
  probe is allowed through (half-open) and its outcome closes or re-opens
  the circuit.  Share one breaker across the clients that target one
  endpoint; never share it across endpoints.

All deadlines are computed from ``time.monotonic()`` — wall-clock
(``time.time()``) deadlines jump under NTP adjustment (tpu-lint TIME-WALL).
"""

import asyncio
import collections
import random
import threading
import time

from client_tpu.utils import InferenceServerException

__all__ = [
    "RetryPolicy",
    "Deadline",
    "CircuitBreaker",
    "CircuitBreakerRegistry",
    "CircuitOpenError",
    "NoHealthyEndpointError",
    "call_with_retry",
    "acall_with_retry",
    "call_with_failover",
    "acall_with_failover",
    "is_connection_error",
    "is_connection_level",
    "backoff_delays",
    "combine_timeouts",
]


def combine_timeouts(a, b):
    """Tighter of two optional timeouts in seconds (None = unbounded).

    The one implementation of "cap a caller timeout by a deadline-derived
    attempt budget" shared by the HTTP clients and the replica-set router.
    """
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)

# Overload / transient statuses worth retrying.  HTTP codes arrive as
# decimal strings (the HTTP clients stringify response.status); gRPC codes
# as StatusCode names.  DEADLINE_EXCEEDED is the gRPC spelling of a
# per-attempt timeout (the HTTP clients surface the same event as a
# wrapped transport timeout): retryable, with the attempt budget and the
# policy Deadline bounding the total spend.
RETRYABLE_STATUSES = frozenset(
    {"429", "503", "UNAVAILABLE", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
)

# Transport exception types whose module marks them as connection-level.
# Checked by module prefix so this module imports neither urllib3, aiohttp,
# nor grpc (transport-agnostic; any subset may be absent at runtime).
_CONN_MODULE_PREFIXES = ("urllib3", "aiohttp", "http.client", "grpc")


def is_connection_error(exc):
    """Whether *exc* is a connection-level transport failure.

    Covers OSError (refused/reset/unreachable), timeouts, and the
    transport libraries' wrapper hierarchies (urllib3 ProtocolError et al.
    do not derive from OSError).
    """
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    if isinstance(exc, (asyncio.TimeoutError, EOFError)):
        return True
    mod = type(exc).__module__ or ""
    return mod.startswith(_CONN_MODULE_PREFIXES)


def is_connection_level(exc):
    """Connection-level classification across wrapped and bare transport
    exceptions: the endpoint never answered (dead/partitioned), as opposed
    to an answered error (overload shed, drain, application failure).  The
    one classifier shared by retry decisions and the replica-set pool's
    UNREACHABLE marking."""
    if exc is None:
        return False
    if isinstance(exc, InferenceServerException):
        details = exc.debug_details()
        return details is not None and is_connection_error(details)
    return is_connection_error(exc)


class CircuitOpenError(InferenceServerException):
    """Fast-fail raised while a circuit breaker is open.

    Subclasses InferenceServerException so callers' existing error handling
    sees the familiar type; ``status`` is the retryable 503 so a *different*
    endpoint's policy layered above may still route around it.
    """

    def __init__(self, msg):
        super().__init__(msg=msg, status="503")


class NoHealthyEndpointError(InferenceServerException):
    """Raised when a replica-set router has no endpoint to offer.

    Every endpoint is drained, unreachable, or behind an open circuit.
    ``status`` is the retryable 503: the condition is transient by
    construction (circuits half-open, drained replicas come back), so a
    retry layer above may keep backing off into the router.
    """

    def __init__(self, msg):
        super().__init__(msg=msg, status="503")


class Deadline:
    """A monotonic wall-time budget shared across retry attempts.

    ``remaining()`` is what is left; ``attempt_timeout(cap)`` derives one
    attempt's transport timeout (never exceeding the budget, optionally
    capped by the caller's own per-try timeout).
    """

    def __init__(self, budget_s):
        if budget_s is None or budget_s <= 0:
            raise ValueError(f"deadline budget must be positive, got {budget_s!r}")
        self.budget_s = float(budget_s)
        self._expires = time.monotonic() + self.budget_s

    def remaining(self):
        return self._expires - time.monotonic()

    def expired(self):
        return self.remaining() <= 0

    def attempt_timeout(self, cap=None):
        """Per-attempt transport timeout from the remaining budget."""
        remaining = max(self.remaining(), 0.0)
        if cap is not None:
            return min(remaining, cap)
        return remaining


def _notify(observer, method, *args):
    """Invoke an optional observer hook; observers are best-effort and
    must never break the call path (or a lock-free state transition)."""
    if observer is None:
        return
    fn = getattr(observer, method, None)
    if fn is None:
        return
    try:
        fn(*args)
    except Exception:
        pass


class _SerialDeliverer:
    """Ordered observer delivery with NO lock held during the callback.

    The old scheme serialized deliveries by holding a ``_notify_lock``
    across the observer call — which handed third-party code a private,
    non-reentrant lock: an observer that triggered another transition
    (or looked back at an object that does) deadlocked on it
    (CALLBACK-UNDER-LOCK).  This replaces it with a FIFO queue + single
    drainer: posters enqueue under a tiny mutex; whichever thread finds
    no drainer active becomes one and delivers queued items with the
    mutex RELEASED, so total order is preserved (one drainer at a time,
    FIFO queue) while observers run lock-free.

    ``post(deliver, accept=None)``: *accept* (optional) runs under the
    mutex at dequeue time and may veto the delivery — the stale-transition
    drop (a preempted thread's older state change must not be delivered
    after a newer one) keeps its exact semantics, because the accept check
    happens in delivery order, not post order.
    """

    __slots__ = ("_mu", "_queue", "_draining")

    def __init__(self):
        self._mu = threading.Lock()
        self._queue = collections.deque()
        self._draining = False

    def post(self, deliver, accept=None):
        with self._mu:
            self._queue.append((deliver, accept))
            if self._draining:
                return  # the active drainer will deliver this, in order
            self._draining = True
        try:
            while True:
                with self._mu:
                    if not self._queue:
                        self._draining = False
                        return
                    deliver, accept = self._queue.popleft()
                    ok = accept is None or accept()
                if ok:
                    deliver()
        except BaseException:
            # a raising deliver/accept must not latch _draining forever
            # (every later post would enqueue into a queue nobody drains);
            # items already queued wait for the next post to drain them
            with self._mu:
                self._draining = False
            raise  # observer code: no lock of ours is held


class CircuitBreaker:
    """Per-endpoint circuit breaker: closed → open → half-open.

    Thread-safe (one lock, no blocking inside it), usable from both the
    sync clients and coroutine code.  ``before_attempt()`` raises
    :class:`CircuitOpenError` while open; after ``reset_timeout_s`` one
    probe passes (half-open) and its outcome decides the next state.

    ``observer`` (optional) receives ``on_state_change(old, new)`` on
    every transition — outside the breaker lock — so metrics (e.g.
    ``client_tpu.serve.metrics.ResilienceMetricsObserver``) and logging
    can watch the circuit without touching its hot path.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, failure_threshold=5, reset_timeout_s=30.0, name="",
                 observer=None):
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.name = name
        self.observer = observer
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False  # a half-open probe is in flight
        # Transition delivery: stamped under _lock, delivered outside it
        # through the serial deliverer, stale deliveries dropped — so a
        # preempted thread can never report an older transition after a
        # newer one (which would wedge a state gauge at the wrong value),
        # and the observer runs with NO breaker lock held (an observer
        # that reads .state or trips another transition must not deadlock).
        self._transition_seq = 0
        self._delivered_seq = 0
        self._deliverer = _SerialDeliverer()

    @property
    def state(self):
        with self._lock:
            return self._state

    def _fast_fail(self):
        raise CircuitOpenError(
            f"circuit breaker{f' {self.name!r}' if self.name else ''} "
            f"is open ({self._failures} consecutive failures); "
            f"fast-failing for {self.reset_timeout_s:g}s"
        )

    def _deliver(self, seq, old, new):
        """Deliver one stamped transition, dropping it if a newer one was
        already delivered (a preempted thread must not overwrite a fresher
        observer state — e.g. park a circuit-state gauge at 'open' after
        the breaker already closed again).  The accept check runs in
        delivery order inside the deliverer's mutex; the observer call
        itself runs outside every lock."""
        if seq is None:
            return

        def accept():
            if seq <= self._delivered_seq:
                return False
            self._delivered_seq = seq
            return True

        self._deliverer.post(
            lambda: _notify(self.observer, "on_state_change", old, new),
            accept,
        )

    def before_attempt(self):
        """Gate one attempt; raises CircuitOpenError without touching the
        network while the circuit is open and the cooldown has not passed.
        After the cooldown exactly ONE probe passes — concurrent callers
        keep fast-failing until that probe's outcome is recorded (no
        thundering herd onto a recovering endpoint)."""
        transition = None
        with self._lock:
            if self._state == self.OPEN:
                if time.monotonic() - self._opened_at < self.reset_timeout_s:
                    self._fast_fail()
                self._transition_seq += 1
                transition = (self._transition_seq, self._state, self.HALF_OPEN)
                self._state = self.HALF_OPEN
                self._probing = True
            elif self._state == self.HALF_OPEN and self._probing:
                self._fast_fail()
        if transition is not None:
            self._deliver(*transition)

    def record_success(self):
        transition = None
        with self._lock:
            old = self._state
            self._failures = 0
            self._state = self.CLOSED
            self._probing = False
            if old != self.CLOSED:
                self._transition_seq += 1
                transition = (self._transition_seq, old, self.CLOSED)
        if transition is not None:
            self._deliver(*transition)

    def record_failure(self):
        transition = None
        with self._lock:
            old = self._state
            self._failures += 1
            self._probing = False
            if self._state == self.HALF_OPEN or self._failures >= self.failure_threshold:
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                if old != self.OPEN:
                    self._transition_seq += 1
                    transition = (self._transition_seq, old, self.OPEN)
        if transition is not None:
            self._deliver(*transition)


class CircuitBreakerRegistry:
    """Per-endpoint :class:`CircuitBreaker` instances sharing one config.

    A replica set needs one breaker *per endpoint* (sharing a breaker
    across endpoints would let one dead replica open the circuit against
    its healthy peers); this registry creates them on demand, keyed by the
    endpoint string, all with the same thresholds.

    ``observer_factory(endpoint)`` (optional) builds the per-endpoint
    observer each new breaker is born with — e.g.
    ``client_tpu.serve.metrics.ResilienceMetricsObserver`` so every
    endpoint's circuit state lands on /metrics under its own label.
    """

    def __init__(self, failure_threshold=5, reset_timeout_s=30.0,
                 observer_factory=None):
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self._observer_factory = observer_factory
        self._lock = threading.Lock()
        self._breakers = {}

    def get(self, endpoint):
        with self._lock:
            breaker = self._breakers.get(endpoint)
            if breaker is None:
                observer = (
                    self._observer_factory(endpoint)
                    if self._observer_factory is not None
                    else None
                )
                breaker = CircuitBreaker(
                    failure_threshold=self.failure_threshold,
                    reset_timeout_s=self.reset_timeout_s,
                    name=endpoint,
                    observer=observer,
                )
                self._breakers[endpoint] = breaker
            return breaker

    def states(self):
        """{endpoint: state} snapshot (the state reads take each breaker's
        own lock; the registry lock only guards the dict)."""
        with self._lock:
            breakers = dict(self._breakers)
        return {endpoint: b.state for endpoint, b in breakers.items()}


class RetryPolicy:
    """Retry/backoff/deadline policy for one client's unary calls.

    Parameters
    ----------
    max_attempts : total tries including the first (1 = no retry).
    initial_backoff_s, backoff_multiplier, max_backoff_s : the exponential
        schedule jittered by ``jitter``.
    jitter : True for full jitter (uniform(0, delay)); False for the bare
        exponential (deterministic — useful in tests).
    retryable_statuses : status strings (HTTP codes / gRPC code names)
        classified retryable in addition to connection errors.
    deadline_s : total wall-time budget across attempts (None = unbounded).
    circuit_breaker : optional CircuitBreaker shared by calls through this
        policy.
    observer : optional hook object; any subset of ``on_backoff(attempt,
        delay_s, exc)`` (a retry is about to sleep), ``on_giveup(attempt,
        exc)`` (the policy stopped retrying), and ``on_success(attempt)``
        is called — best-effort, never on the raising path's stack state.
        ``client_tpu.serve.metrics.ResilienceMetricsObserver`` feeds these
        into the /metrics retry counters.
    """

    def __init__(
        self,
        max_attempts=4,
        initial_backoff_s=0.05,
        backoff_multiplier=2.0,
        max_backoff_s=2.0,
        jitter=True,
        retryable_statuses=RETRYABLE_STATUSES,
        deadline_s=None,
        circuit_breaker=None,
        rng=None,
        observer=None,
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.initial_backoff_s = float(initial_backoff_s)
        self.backoff_multiplier = float(backoff_multiplier)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = bool(jitter)
        self.retryable_statuses = frozenset(retryable_statuses)
        self.deadline_s = deadline_s
        self.circuit_breaker = circuit_breaker
        self.observer = observer
        self._rng = rng or random.Random()

    # -- classification ----------------------------------------------------

    def retryable(self, exc):
        """Whether one failed attempt is worth retrying."""
        if isinstance(exc, CircuitOpenError):
            return False  # fast-fail is the point; do not spin on the breaker
        if isinstance(exc, InferenceServerException):
            status = exc.status()
            if status is not None:
                return str(status) in self.retryable_statuses
        return is_connection_level(exc)

    # -- schedule ----------------------------------------------------------

    def backoff_s(self, attempt):
        """Sleep before retry number *attempt* (0-based)."""
        delay = min(
            self.max_backoff_s,
            self.initial_backoff_s * (self.backoff_multiplier ** attempt),
        )
        if self.jitter:
            delay = self._rng.uniform(0.0, delay)
        return delay

    def delay_for(self, exc, attempt):
        """Backoff for this retry, honoring the server's Retry-After hint
        (capped at max_backoff_s — a bad hint must not park the client)."""
        hint = getattr(exc, "retry_after_s", None)
        if hint is not None:
            try:
                return min(float(hint), self.max_backoff_s)
            except (TypeError, ValueError):
                pass
        return self.backoff_s(attempt)

    def new_deadline(self):
        return Deadline(self.deadline_s) if self.deadline_s else None


def backoff_delays(initial_s=0.05, multiplier=2.0, max_s=2.0, rng=None):
    """Infinite generator of full-jitter exponential delays.

    The reusable loop shape for ad-hoc retry sites (e.g. the perf
    rendezvous connect loop) that don't need the full policy object.
    """
    rng = rng or random.Random()
    delay = initial_s
    while True:
        yield rng.uniform(0.0, delay)
        delay = min(delay * multiplier, max_s)


def _record_outcome(breaker, retryable):
    """Breaker accounting for one failed attempt: only transport/overload
    failures count against the circuit.  A non-retryable application error
    (bad input, unknown model) means the endpoint answered — that is
    evidence of health, and must not open the circuit against a healthy
    server (or strand a half-open probe)."""
    if breaker is None:
        return
    if retryable:
        breaker.record_failure()
    else:
        breaker.record_success()


def _next_step(policy, deadline, exc, attempt, retryable):
    """Shared retry decision: returns the backoff sleep, or raises *exc*
    when the classification, attempt budget, or deadline budget says stop."""
    if not retryable or attempt + 1 >= policy.max_attempts:
        _notify(policy.observer, "on_giveup", attempt, exc)
        raise exc
    delay = policy.delay_for(exc, attempt)
    if deadline is not None:
        remaining = deadline.remaining()
        # a backoff that would outlive the budget is a guaranteed-dead
        # retry: surface the real error now instead of sleeping into it
        if remaining <= 0 or delay >= remaining:
            _notify(policy.observer, "on_giveup", attempt, exc)
            raise exc
    _notify(policy.observer, "on_backoff", attempt, delay, exc)
    return delay


def call_with_retry(fn, policy):
    """Run ``fn(attempt_timeout_s_or_None)`` under *policy* (sync).

    *fn* receives the per-attempt transport timeout derived from the
    policy's deadline (None when the policy has no deadline) and must raise
    on failure — including application-level retryable statuses the caller
    wants retried (e.g. an HTTP 503 response mapped to an exception).
    """
    deadline = policy.new_deadline()
    breaker = policy.circuit_breaker
    attempt = 0
    while True:
        if breaker is not None:
            breaker.before_attempt()
        try:
            result = fn(deadline.attempt_timeout() if deadline else None)
        except CircuitOpenError:
            raise
        except Exception as exc:
            retryable = policy.retryable(exc)
            _record_outcome(breaker, retryable)
            if breaker is not None and breaker.state == CircuitBreaker.OPEN:
                # this failure opened (or re-opened) the circuit: further
                # retries would only fast-fail after a pointless backoff —
                # surface the real error now
                _notify(policy.observer, "on_giveup", attempt, exc)
                raise
            delay = _next_step(policy, deadline, exc, attempt, retryable)
            attempt += 1
            time.sleep(delay)
        else:
            if breaker is not None:
                breaker.record_success()
            _notify(policy.observer, "on_success", attempt)
            return result


def _failover_step(policy, deadline, exc, attempt, retryable, fresh):
    """Retry decision for one failed *routed* attempt: returns the backoff
    sleep before the next attempt, or raises *exc* when the classification,
    attempt budget, or deadline budget says stop.

    ``fresh`` is True when the router still has an untried healthy replica
    for this request: the failover hop to it is immediate (sleeping in
    front of a different, healthy endpoint only adds latency).  Once the
    request has cycled through every candidate the normal backoff schedule
    applies — hammering replicas that all just failed is the retry storm
    the schedule exists to prevent."""
    if not retryable or attempt + 1 >= policy.max_attempts:
        _notify(policy.observer, "on_giveup", attempt, exc)
        raise exc
    delay = 0.0 if fresh else policy.delay_for(exc, attempt)
    if deadline is not None:
        remaining = deadline.remaining()
        if remaining <= 0 or (delay > 0 and delay >= remaining):
            _notify(policy.observer, "on_giveup", attempt, exc)
            raise exc
    _notify(policy.observer, "on_backoff", attempt, delay, exc)
    return delay


def call_with_failover(fn, policy, route):
    """Run one logical request under *policy*, rotating endpoints per attempt.

    The replica-set twin of :func:`call_with_retry`: instead of retrying one
    fixed endpoint, every attempt is routed —

    - ``route(excluded_keys)`` returns a *lease*: an object with ``key``
      (stable endpoint identity for exclusion), ``last_candidate`` (True
      when no other non-excluded healthy endpoint existed at pick time),
      and ``success()`` / ``failure(exc, retryable)`` outcome hooks (the
      router's inflight/breaker/health accounting).  It raises
      :class:`NoHealthyEndpointError` when nothing is routable.
    - ``fn(lease, attempt_timeout_s_or_None)`` performs one transport
      attempt against ``lease.endpoint`` and raises on failure.

    A failed attempt's endpoint is excluded from the next ``route()`` call,
    so a retry lands on a different healthy replica while one exists (and
    the hop is immediate — see :func:`_failover_step`); when every
    candidate has been tried the exclusions wrap and the backoff schedule
    takes over.  ``NoHealthyEndpointError`` from the router is itself
    retried on the schedule (circuits half-open, drained replicas return)
    until the attempt or deadline budget runs out.
    """
    deadline = policy.new_deadline()
    excluded = []
    attempt = 0
    last_exc = None
    while True:
        try:
            lease = route(tuple(excluded))
        except NoHealthyEndpointError as exc:
            if last_exc is not None:
                exc.__cause__ = last_exc
            delay = _failover_step(policy, deadline, exc, attempt,
                                   retryable=True, fresh=False)
            attempt += 1
            time.sleep(delay)
            excluded = []  # the endpoint set may have recovered: retry all
            continue
        try:
            result = fn(lease, deadline.attempt_timeout() if deadline else None)
        except Exception as exc:
            retryable = policy.retryable(exc)
            lease.failure(exc, retryable)
            last_exc = exc
            fresh = not lease.last_candidate
            if lease.key not in excluded:
                excluded.append(lease.key)
            else:  # wrapped onto an already-tried replica: restart rotation
                excluded = [lease.key]
            delay = _failover_step(policy, deadline, exc, attempt, retryable,
                                   fresh)
            attempt += 1
            if delay > 0:
                time.sleep(delay)
        else:
            lease.success()
            _notify(policy.observer, "on_success", attempt)
            return result


async def acall_with_failover(fn, policy, route):
    """Async twin of :func:`call_with_failover`; ``fn`` is a coroutine
    function ``fn(lease, timeout)``; ``route`` stays synchronous (endpoint
    selection never blocks)."""
    deadline = policy.new_deadline()
    excluded = []
    attempt = 0
    last_exc = None
    while True:
        try:
            lease = route(tuple(excluded))
        except NoHealthyEndpointError as exc:
            if last_exc is not None:
                exc.__cause__ = last_exc
            delay = _failover_step(policy, deadline, exc, attempt,
                                   retryable=True, fresh=False)
            attempt += 1
            await asyncio.sleep(delay)
            excluded = []
            continue
        try:
            result = await fn(
                lease, deadline.attempt_timeout() if deadline else None
            )
        except Exception as exc:
            retryable = policy.retryable(exc)
            lease.failure(exc, retryable)
            last_exc = exc
            fresh = not lease.last_candidate
            if lease.key not in excluded:
                excluded.append(lease.key)
            else:
                excluded = [lease.key]
            delay = _failover_step(policy, deadline, exc, attempt, retryable,
                                   fresh)
            attempt += 1
            if delay > 0:
                await asyncio.sleep(delay)
        else:
            lease.success()
            _notify(policy.observer, "on_success", attempt)
            return result


async def acall_with_retry(fn, policy):
    """Async twin of :func:`call_with_retry`; ``fn`` is a coroutine
    function taking the derived per-attempt timeout."""
    deadline = policy.new_deadline()
    breaker = policy.circuit_breaker
    attempt = 0
    while True:
        if breaker is not None:
            breaker.before_attempt()
        try:
            result = await fn(deadline.attempt_timeout() if deadline else None)
        except CircuitOpenError:
            raise
        except Exception as exc:
            retryable = policy.retryable(exc)
            _record_outcome(breaker, retryable)
            if breaker is not None and breaker.state == CircuitBreaker.OPEN:
                # failure opened the circuit: surface the real error now
                # instead of backing off into a guaranteed fast-fail
                _notify(policy.observer, "on_giveup", attempt, exc)
                raise
            delay = _next_step(policy, deadline, exc, attempt, retryable)
            attempt += 1
            await asyncio.sleep(delay)
        else:
            if breaker is not None:
                breaker.record_success()
            _notify(policy.observer, "on_success", attempt)
            return result
