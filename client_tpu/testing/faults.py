"""Fault injection: an in-process chaos TCP proxy + server-side hooks.

:class:`FaultProxy` sits between a real client and a real server socket
and injects transport failures on command — connect delay, reset the
next N connections (error-N-times-then-succeed), refuse everything,
truncate a response mid-body, or kill live connections mid-stream.  The
faults happen on real sockets, so every layer under test (urllib3 pool,
aiohttp session, grpc channel, h2 stream) sees the failure exactly as it
would in production.

Server-side hooks (:class:`FailNTimes`, :class:`GatedFn`) wrap a model
``fn`` to fail with a chosen status N times before succeeding, or to
block until released (the drain-while-busy and overload shapes).

This module is stdlib-only and import-safe anywhere the clients are.
"""

import socket
import struct
import threading
import time

from client_tpu.utils import InferenceServerException

__all__ = ["FaultProxy", "FailNTimes", "GatedFn"]


class FaultProxy:
    """Chaos TCP proxy forwarding ``host:port`` -> *upstream_address*.

    All fault knobs are thread-safe and take effect on the next
    connection (or, for :meth:`kill_active`, immediately).  With no
    faults armed it is a transparent byte pump.
    """

    def __init__(self, upstream_address, host="127.0.0.1", port=0):
        up_host, _, up_port = str(upstream_address).rpartition(":")
        self._upstream = (up_host or "127.0.0.1", int(up_port))
        self._lock = threading.Lock()
        self._closed = False
        self._refuse = False
        self._reset_next = 0
        self._delay_s = 0.0
        self._cut_plans = []  # [remaining_response_bytes] budgets, one per conn
        self._active = []  # live (client_sock, upstream_sock) pairs
        self.connections = 0  # accepted count (test observability)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self._thread = threading.Thread(
            target=self._serve, name="fault-proxy", daemon=True
        )
        self._thread.start()

    @property
    def address(self):
        host, port = self._srv.getsockname()[:2]
        return f"{host}:{port}"

    # -- fault plan ---------------------------------------------------------

    def reset_next_connections(self, n):
        """RST the next *n* connections at accept (error-then-succeed)."""
        with self._lock:
            self._reset_next = int(n)

    def refuse_connections(self, refuse=True):
        """Reset every connection until cleared (persistent outage)."""
        with self._lock:
            self._refuse = bool(refuse)

    def set_delay(self, seconds):
        """Hold each new connection *seconds* before bridging upstream."""
        with self._lock:
            self._delay_s = float(seconds)

    def cut_responses_after(self, nbytes, times=1):
        """Truncate: for the next *times* connections forward only
        *nbytes* of response bytes, then kill the connection mid-body."""
        with self._lock:
            self._cut_plans.extend([int(nbytes)] for _ in range(times))

    def kill_active(self):
        """Mid-stream disconnect: hard-close every live bridged pair."""
        with self._lock:
            pairs, self._active = self._active, []
        for pair in pairs:
            for sock in pair:
                _hard_close(sock)

    def sigkill(self):
        """The SIGKILL shape as seen from the network: every live
        connection dies with an RST and every new one is refused — what
        a process kill (plus the kernel reaping its sockets) looks like
        to clients.  The upstream server process itself is untouched;
        pair with stopping it (without drain) for full fidelity."""
        self.refuse_connections(True)
        self.kill_active()

    def close(self):
        with self._lock:
            self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        self.kill_active()
        self._thread.join(timeout=5)

    # -- data path ----------------------------------------------------------

    def _serve(self):
        # one guard over the whole accept pass (the BG-THREAD-CRASH
        # shape): a chaos proxy whose accept thread dies silently turns
        # every scenario into a refused-connection test
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:  # listener closed
                return
            try:
                with self._lock:
                    if self._closed:
                        _hard_close(conn)
                        return
                    self.connections += 1
                    reset = self._refuse
                    if self._reset_next > 0:
                        self._reset_next -= 1
                        reset = True
                    delay = self._delay_s
                    # a reset connection must not consume a truncation
                    # plan: the plan applies to the next connection that
                    # bridges
                    budget = (
                        self._cut_plans.pop(0)
                        if self._cut_plans and not reset
                        else None
                    )
                if reset:
                    _hard_close(conn)
                    continue
                threading.Thread(
                    target=self._bridge,
                    args=(conn, delay, budget),
                    name="fault-proxy-conn",
                    daemon=True,
                ).start()
            except Exception:
                _hard_close(conn)

    def _bridge(self, conn, delay, budget):
        if delay:
            time.sleep(delay)
        try:
            upstream = socket.create_connection(self._upstream, timeout=10)
        except OSError:
            _hard_close(conn)
            return
        pair = (conn, upstream)
        with self._lock:
            if self._closed:
                for sock in pair:
                    _hard_close(sock)
                return
            self._active.append(pair)
        request_pump = threading.Thread(
            target=self._pump, args=(conn, upstream, None, pair),
            name="fault-proxy-up", daemon=True,
        )
        request_pump.start()
        # response direction carries the truncation budget
        self._pump(upstream, conn, budget, pair)

    def _pump(self, src, dst, budget, pair):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                if budget is not None:
                    data = data[: max(budget[0], 0)]
                    budget[0] -= len(data)
                if data:
                    dst.sendall(data)
                if budget is not None and budget[0] <= 0:
                    break  # truncation point reached: kill the pair
        except OSError:
            pass
        with self._lock:
            live = pair in self._active
            if live:
                self._active.remove(pair)
        if live:
            for sock in pair:
                _hard_close(sock)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _hard_close(sock):
    """Terminate a connection abruptly (SO_LINGER 0 => RST on close).

    ``shutdown()`` first: ``close()`` alone does not tear down the TCP
    connection while another thread is blocked in ``recv()`` on the same
    socket (the in-flight syscall pins the file) — the peer would see
    nothing until that thread woke.  shutdown terminates the connection
    immediately and wakes any blocked pump thread."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class FailNTimes:
    """Server-side fault hook: fail the first *n* calls with *status*,
    then delegate to the wrapped model fn (application-level
    error-then-succeed, e.g. a model still loading its weights)."""

    def __init__(self, fn, n, status="503", msg="injected transient failure"):
        self._fn = fn
        self._status = status
        self._msg = msg
        self._lock = threading.Lock()
        self.calls = 0
        self.failures_remaining = int(n)

    def __call__(self, inputs, params, context):
        with self._lock:
            self.calls += 1
            if self.failures_remaining > 0:
                self.failures_remaining -= 1
                raise InferenceServerException(self._msg, status=self._status)
        return self._fn(inputs, params, context)


class GatedFn:
    """Server-side hook holding every call until :meth:`release` — the
    in-flight-work shape for drain and overload tests.  ``entered`` is set
    once at least one call is inside the model."""

    def __init__(self, fn, timeout_s=30.0):
        self._fn = fn
        self._timeout_s = timeout_s
        self.entered = threading.Event()
        self._gate = threading.Event()

    def release(self):
        self._gate.set()

    def __call__(self, inputs, params, context):
        self.entered.set()
        # bounded so a broken test cannot wedge the server thread forever
        self._gate.wait(timeout=self._timeout_s)
        return self._fn(inputs, params, context)
