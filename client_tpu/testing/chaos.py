"""Deterministic chaos matrix: declarative fault schedules + invariant
checkers, replacing per-test chaos boilerplate.

Every chaos scenario in this repo used to be hand-rolled: spawn driver
threads, sleep, kill something, join with a timeout, collect errors,
assert a scenario-specific pile of invariants.  This module factors that
into three reusable pieces:

- :class:`FaultSpec` / :class:`ChaosScenario` — a *declarative* schedule:
  fault kind x injection point x timing.  Timings may be literal offsets
  or seeded ``("uniform", lo, hi)`` draws, resolved once per scenario
  from ``ChaosScenario.seed`` — the same seed always yields the same
  schedule, so a failing matrix entry reproduces exactly.
- :func:`run_scenario` — drives caller-provided *driver* callables on
  threads while firing the schedule through an ``apply_fault`` hook
  (:func:`dispatch_fault` covers the standard
  :class:`~client_tpu.testing.faults.FaultProxy`-fronted shapes: SIGKILL,
  mid-stream connection kill, refuse/restore, delay, truncation, drain).
  Driver exceptions are collected, never raised mid-run, and a driver
  that outlives the join timeout is reported as *wedged* — the
  hang-across-the-kill failure mode chaos tests exist to catch.
- invariant checkers, run after every scenario: :class:`StepLedger`
  (no ``(sequence, step)`` applied twice — with the resumed-after-kill
  carve-out for applies orphaned on a dead replica),
  :func:`assert_byte_exact` (stream/sequence resume produced the exact
  reference bytes), :func:`assert_kv_clean` (the LM engine's paged pool
  is fully free and its refcount ledger balanced),
  :func:`assert_lock_witness_acyclic` (the dynamic lock-order witness
  saw a DAG, no cycles).

:class:`ChaosMatrix` strings scenarios into a suite: one fixture per
scenario, invariants checked after each, teardown guaranteed.  Adding a
scenario to an existing matrix is one :class:`ChaosScenario` line.

This module is stdlib-only (numpy excepted) and import-safe anywhere the
clients are.
"""

import random
import threading
import time

from client_tpu.analysis.witness import witness_shared

__all__ = [
    "FaultSpec",
    "ChaosScenario",
    "ScenarioResult",
    "StepLedger",
    "ChaosMatrix",
    "run_scenario",
    "dispatch_fault",
    "assert_byte_exact",
    "assert_kv_clean",
    "assert_lock_witness_acyclic",
    "assert_race_witness_clean",
    "assert_no_leaked_resources",
]


class FaultSpec:
    """One scheduled fault: ``kind`` x injection point (``target``) x
    timing (``at_s``).

    ``at_s`` is a float offset from scenario start, or a seeded draw
    ``("uniform", lo, hi)`` resolved by :meth:`ChaosScenario.schedule`.
    ``target`` is the injection point in the fixture's vocabulary
    (usually a replica index).  Kind-specific extras ride in ``params``
    (e.g. ``FaultSpec("delay", at_s=0.1, target=1, seconds=0.5)``).

    Standard kinds (:func:`dispatch_fault`): ``kill_replica`` (SIGKILL —
    connections RST, new ones refused, no drain), ``kill_connections``
    (mid-stream disconnect only), ``refuse`` / ``restore``,
    ``reset_next`` (RST the next ``n`` connections), ``delay``
    (``seconds``), ``truncate`` (``nbytes``/``times``), ``drain``
    (planned retire), ``custom`` (``fn`` called with the fixture's
    dispatch kwargs).
    """

    def __init__(self, kind, at_s=0.0, target=0, **params):
        self.kind = str(kind)
        self.at_s = at_s
        self.target = target
        self.params = params

    def __repr__(self):
        return (
            f"FaultSpec({self.kind!r}, at_s={self.at_s!r}, "
            f"target={self.target!r}"
            + ("".join(f", {k}={v!r}" for k, v in self.params.items()))
            + ")"
        )


class ChaosScenario:
    """A named, seeded fault schedule.

    ``seed`` makes randomized timings (and anything else the fixture
    draws from :meth:`rng`) deterministic: the matrix is reproducible
    run to run, and a red scenario replays bit-identically.
    """

    def __init__(self, name, faults=(), seed=0, **params):
        self.name = str(name)
        self.faults = list(faults)
        self.seed = int(seed)
        self.params = params  # fixture-specific knobs (session count...)

    def rng(self):
        """A fresh seeded RNG — fixtures draw workload shapes from this
        so the whole scenario, not just fault timing, is deterministic."""
        return random.Random(self.seed)

    def schedule(self):
        """``[(at_s, FaultSpec)]`` sorted by time, timings resolved with
        the scenario seed (same seed -> same schedule, always)."""
        rng = self.rng()
        out = []
        for fault in self.faults:
            at = fault.at_s
            if isinstance(at, (tuple, list)):
                dist, lo, hi = at
                if dist != "uniform":
                    raise ValueError(f"unknown timing draw {dist!r}")
                at = rng.uniform(float(lo), float(hi))
            out.append((float(at), fault))
        out.sort(key=lambda pair: pair[0])
        return out

    def __repr__(self):
        return (
            f"ChaosScenario({self.name!r}, seed={self.seed}, "
            f"faults={self.faults!r})"
        )


class ScenarioResult:
    """One scenario run's outcome: collected driver errors, the faults
    actually fired (with real offsets), wedged-driver count, duration."""

    def __init__(self, name, errors, fired, duration_s, wedged=0):
        self.name = name
        self.errors = list(errors)
        self.fired = list(fired)
        self.duration_s = float(duration_s)
        self.wedged = int(wedged)

    def assert_clean(self):
        """Zero client-visible errors AND no driver wedged across a
        fault — the baseline invariant of every resilience scenario."""
        assert self.wedged == 0, (
            f"{self.name}: {self.wedged} driver(s) wedged past the join "
            "timeout (hung across a fault)"
        )
        assert not self.errors, f"{self.name}: driver errors: {self.errors}"

    def __repr__(self):
        return (
            f"ScenarioResult({self.name!r}, errors={len(self.errors)}, "
            f"wedged={self.wedged}, fired={len(self.fired)}, "
            f"duration_s={self.duration_s:.2f})"
        )


def partition_fleet(tiers, groups):
    """Sever the peer transport between replica *groups*.

    *tiers* is the fixture's ordered FleetTier list; *groups* is a list
    of index lists (e.g. ``[[0], [1, 2]]``) — tiers in different groups
    can no longer reach each other (symmetric), tiers in the same group
    still can.  An index in no group is isolated from everyone.  The
    severing installs a transport filter on each tier
    (:meth:`FleetTier.set_transport_filter`), so outbound peer calls
    fail with OSError exactly where a dropped network would and the
    per-peer breakers accumulate real evidence.  ``heal_fleet`` undoes
    it."""
    group_of = {}
    for gi, members in enumerate(groups):
        for idx in members:
            group_of[int(idx)] = gi
    addr_group = {}
    for ti, tier in enumerate(tiers):
        addr = tier.address
        if addr is not None:
            addr_group[addr] = group_of.get(ti)
    for ti, tier in enumerate(tiers):
        mine = group_of.get(ti)

        def allow(addr, _mine=mine):
            their = addr_group.get(addr)
            if their is None and addr not in addr_group:
                return True  # not a partitioned tier: unaffected
            return _mine is not None and their == _mine

        tier.set_transport_filter(allow)


def heal_fleet(tiers):
    """Clear every partition filter installed by ``partition_fleet`` —
    the network is whole again; convergence (anti-entropy, gossip
    retry, quorum retries) is the code under test, not the harness."""
    for tier in tiers:
        tier.set_transport_filter(None)


def dispatch_fault(fault, proxies=(), kill=None, drain=None, tiers=()):
    """Standard fault dispatch for FaultProxy-fronted replica sets.

    *proxies* maps ``fault.target`` to a
    :class:`~client_tpu.testing.faults.FaultProxy`; *kill*/*drain* are
    optional ``fn(target)`` hooks for the replica-lifecycle kinds (a
    SIGKILL is proxy ``sigkill`` + the *kill* hook stopping the server
    WITHOUT drain; a ``drain`` is the planned-retire path).  *tiers* is
    the ordered FleetTier list for the network-severing kinds:
    ``FaultSpec("partition", groups=[[0], [1, 2]])`` severs the peer
    transport between the index groups, ``FaultSpec("heal")`` restores
    it.  Fixtures with non-standard kinds use
    ``FaultSpec("custom", fn=...)``.
    """
    kind = fault.kind
    proxy = None
    if proxies:
        try:
            proxy = proxies[fault.target]
        except (KeyError, IndexError, TypeError):
            proxy = None
    if kind == "kill_replica":
        if proxy is not None:
            proxy.sigkill()
        if kill is not None:
            kill(fault.target)
        return
    if kind == "kill_connections":
        proxy.kill_active()
        return
    if kind == "refuse":
        proxy.refuse_connections(True)
        return
    if kind == "restore":
        proxy.refuse_connections(False)
        return
    if kind == "reset_next":
        proxy.reset_next_connections(int(fault.params.get("n", 1)))
        return
    if kind == "delay":
        proxy.set_delay(float(fault.params.get("seconds", 0.0)))
        return
    if kind == "truncate":
        proxy.cut_responses_after(
            int(fault.params["nbytes"]), int(fault.params.get("times", 1))
        )
        return
    if kind == "drain":
        if drain is None:
            raise ValueError("scenario uses 'drain' but no drain hook given")
        drain(fault.target)
        return
    if kind == "partition":
        partition_fleet(tiers, fault.params["groups"])
        return
    if kind == "heal":
        heal_fleet(tiers)
        return
    if kind == "custom":
        fault.params["fn"]()
        return
    raise ValueError(f"unknown fault kind {fault.kind!r}")


def run_scenario(scenario, apply_fault, drivers, join_timeout_s=600.0):
    """Run *drivers* (callables) on threads while firing *scenario*'s
    fault schedule through ``apply_fault(fault)``.

    Driver exceptions are collected into the result (a chaos driver
    failing must not abort the matrix mid-scenario — the invariant pass
    decides what counts).  Fault-application errors are collected under
    a ``"fault:<kind>"`` pseudo-driver key.  Returns
    :class:`ScenarioResult`.
    """
    errors = []
    threads = []

    def _wrap(index, fn):
        def run():
            try:
                fn()
            except Exception as exc:  # noqa: BLE001 - collected, checked
                errors.append((index, exc))

        return run

    for i, fn in enumerate(drivers):
        threads.append(threading.Thread(
            target=_wrap(i, fn), name=f"chaos-driver-{i}", daemon=True,
        ))
    fired = []
    t0 = time.monotonic()
    for thread in threads:
        thread.start()
    for at_s, fault in scenario.schedule():
        delay = t0 + at_s - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            apply_fault(fault)
        except Exception as exc:  # noqa: BLE001 - collected, checked
            errors.append((f"fault:{fault.kind}", exc))
        fired.append((time.monotonic() - t0, fault))
    deadline = time.monotonic() + float(join_timeout_s)
    for thread in threads:
        thread.join(timeout=max(deadline - time.monotonic(), 0.001))
    wedged = sum(1 for thread in threads if thread.is_alive())
    return ScenarioResult(
        scenario.name, errors, fired, time.monotonic() - t0, wedged=wedged
    )


@witness_shared("_lock")
class StepLedger:
    """Cross-replica ``(sequence, step)`` application ledger.

    Model functions (or fixtures) call :meth:`record` when a step is
    actually APPLIED to sequence state — idempotent replays served from
    the retained rendering never touch the model, so they never record.
    :meth:`assert_exactly_once` is the exactly-once invariant checker.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._applies = []  # (seq_id, step, replica, t)

    def record(self, seq_id, step, replica):
        with self._lock:
            self._applies.append(
                (seq_id, int(step), replica, time.monotonic())
            )

    def applies(self):
        with self._lock:
            return list(self._applies)

    def assert_exactly_once(self, orphans=()):
        """No ``(sequence, step)`` applied twice.

        *orphans* names replicas that were KILLED unplanned: an apply on
        an orphan that was never acknowledged dies with the replica, and
        the survivor legitimately re-applies the step on the replicated
        snapshot — so a pair whose earlier applies all sit on orphans is
        a resume, not a duplicate.  Duplicates on one replica, or any
        re-apply whose predecessor ran on a SURVIVOR, always fail.
        """
        orphans = set(orphans)
        by_step = {}
        for seq_id, step, replica, t in self.applies():
            by_step.setdefault((seq_id, step), []).append((t, replica))
        bad = []
        for key, entries in sorted(by_step.items()):
            if len(entries) == 1:
                continue
            entries.sort()
            replicas = [replica for _t, replica in entries]
            if len(set(replicas)) != len(replicas):
                bad.append((key, replicas, "same replica applied it twice"))
            elif any(replica not in orphans for replica in replicas[:-1]):
                bad.append((
                    key, replicas,
                    "an earlier apply ran on a SURVIVING replica",
                ))
        assert not bad, f"(sequence, step) applied twice: {bad}"

    def steps_for(self, seq_id):
        """Sorted distinct applied steps of one sequence."""
        return sorted({
            step for sid, step, _r, _t in self.applies() if sid == seq_id
        })


def assert_byte_exact(got, want, label=""):
    """Resumed output must be byte-exact vs the unbroken reference —
    duplicated or dropped positions fail loudly with a position diff."""
    got = list(got)
    want = list(want)
    if got == want:
        return
    at = next(
        (i for i, (a, b) in enumerate(zip(got, want)) if a != b),
        min(len(got), len(want)),
    )
    raise AssertionError(
        f"{label or 'stream'}: not byte-exact: first divergence at "
        f"position {at} (got {len(got)} values, want {len(want)}): "
        f"got[{at}:{at + 4}]={got[at:at + 4]} "
        f"want[{at}:{at + 4}]={want[at:at + 4]}"
    )


def assert_kv_clean(engine):
    """The LM engine's paged KV pool must end fully free with a balanced
    refcount ledger (call after ``engine.close()``)."""
    kv = getattr(engine, "kv", None)
    if kv is None:
        return  # engine never started: nothing to leak
    assert kv.used_blocks == 0, (
        f"KV pool not fully free after close: {kv.used_blocks} blocks "
        f"held, refcounts {kv.ref_counts()}"
    )


def assert_lock_witness_acyclic(witness):
    """The dynamic lock-order witness observed an acyclic acquisition
    graph (no-op witness=None so matrices run unarmed too)."""
    if witness is None:
        return 0
    return witness.assert_acyclic()


def assert_race_witness_clean(witness):
    """The dynamic race witness (``TPULINT_RACE_WITNESS=1``) recorded no
    unguarded shared writes — covering violations a driver's own
    try/except swallowed mid-scenario.  No-op for None or a plain
    LockWitness so matrices run unarmed (or lock-order-only) too."""
    check = getattr(witness, "assert_race_free", None)
    if check is None:
        return 0
    return check()


def assert_no_leaked_resources(witness):
    """The dynamic resource witness (``TPULINT_RESOURCE_WITNESS=1``)
    holds no live handles — every KV block reservation, endpoint lease
    and tracer span acquired during the scenario was released, even on
    the fault paths the schedule injected.  No-op for None so matrices
    run unarmed too."""
    check = getattr(witness, "assert_clean", None)
    if check is None:
        return 0
    return check()


def _fixture_recorders(fixture):
    """Every flight recorder reachable from *fixture*: an explicit
    ``flight_recorders()`` hook wins; otherwise the standard shapes —
    ``fixture.servers`` (Server objects) and ``fixture.engines`` — are
    scanned for ``engine.flight``."""
    hook = getattr(fixture, "flight_recorders", None)
    if callable(hook):
        try:
            return list(hook())
        except Exception:
            return []
    recorders = []
    for server in getattr(fixture, "servers", None) or ():
        flight = getattr(getattr(server, "engine", None), "flight", None)
        if flight is not None:
            recorders.append(flight)
    for engine in getattr(fixture, "engines", None) or ():
        flight = getattr(engine, "flight", None)
        if flight is not None:
            recorders.append(flight)
    return recorders


class ChaosMatrix:
    """A suite of scenarios over one fixture family.

    ``run(make_fixture)`` builds a FRESH fixture per scenario via
    ``make_fixture(scenario)`` — an object (or namespace) providing:

    - ``apply_fault(fault)`` — usually a :func:`dispatch_fault` closure;
    - ``drivers()`` — the workload callables to run on threads;
    - ``check(result)`` — the scenario's invariant pass (raise to fail);
    - ``close()`` (optional) — teardown, always called;
    - ``flight_recorders()`` (optional) — recorders to dump when an
      invariant fails (default: every ``server.engine.flight`` /
      ``engine.flight`` on the fixture).

    Invariants passed to the constructor run after EVERY scenario's own
    ``check`` — the cross-cutting floor (exactly-once, pool-free, lock
    witness) that no scenario may opt out of.

    A failed ``check``/invariant DUMPS every reachable flight recorder
    before the failure propagates: the red matrix entry ships its own
    postmortem (recent spans, tick timings, preemptions, faults) instead
    of asking for a re-run with tracing on.  ``make chaos``/``make soak``
    point ``TPU_FLIGHT_DIR`` at ``build/flight/`` so the dumps survive
    the failed run.
    """

    def __init__(self, scenarios, invariants=()):
        self.scenarios = list(scenarios)
        self.invariants = list(invariants)

    def _dump_on_failure(self, fixture, scenario, exc):
        for recorder in _fixture_recorders(fixture):
            try:
                recorder.note(
                    "chaos_invariant_failure", scenario=scenario.name,
                    error=repr(exc),
                )
                recorder.dump(f"chaos-{scenario.name}")
            except Exception:
                pass  # the invariant failure is the story, not the dump

    def run(self, make_fixture, join_timeout_s=600.0):
        results = []
        for scenario in self.scenarios:
            fixture = make_fixture(scenario)
            try:
                result = run_scenario(
                    scenario, fixture.apply_fault, fixture.drivers(),
                    join_timeout_s=join_timeout_s,
                )
                try:
                    fixture.check(result)
                    for invariant in self.invariants:
                        invariant(fixture, result)
                except BaseException as exc:
                    self._dump_on_failure(fixture, scenario, exc)
                    raise
            finally:
                close = getattr(fixture, "close", None)
                if close is not None:
                    close()
            results.append(result)
        return results
