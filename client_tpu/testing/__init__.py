"""Test harnesses for exercising the client/server stack under failure.

``client_tpu.testing.faults`` holds the in-process chaos TCP proxy and the
server-side fault hooks that tests/test_resilience.py drives the
resilience policies (client_tpu.resilience) through.
"""
