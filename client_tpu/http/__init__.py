"""Synchronous HTTP/REST client for KServe-v2 servers (Triton-compatible).

Capability parity with ``tritonclient.http`` (reference
src/python/library/tritonclient/http/__init__.py): full management surface,
binary tensor-data extension, request/response compression, shared-memory verbs
(system + cuda passthrough + the client_tpu ``tpu`` flavor), ``async_infer``,
and the static ``generate_request_body``/``parse_response_body`` pair for
request pipelining. Transport is a urllib3 connection pool (the image has no
geventhttpclient); ``async_infer`` multiplexes over a thread pool sized by the
``concurrency`` constructor argument.
"""

import base64
import json
from concurrent.futures import ThreadPoolExecutor
from urllib.parse import quote, urlencode

import urllib3

from client_tpu import _codec
from client_tpu import resilience as _resilience
from client_tpu import tracing as _tracing
from client_tpu._infer_types import (  # noqa: F401  (re-exported API surface)
    InferInput,
    InferRequestedOutput,
    _np_from_json_data,
)
from client_tpu.utils import (
    SERVER_NOT_READY,
    SERVER_READY,
    SERVER_UNREACHABLE,
    InferenceServerException,
    from_wire_bytes,
    raise_error,
    stamp_tenant as _stamp_tenant,
)

__all__ = [
    "InferenceServerClient",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "InferAsyncRequest",
]


def _get_error_from_response(response_body, status, headers=None):
    try:
        msg = json.loads(response_body.decode("utf-8", errors="replace")).get(
            "error", response_body.decode("utf-8", errors="replace")
        )
    except Exception:
        msg = response_body.decode("utf-8", errors="replace")
    exc = InferenceServerException(msg=msg, status=str(status))
    retry_after = (headers or {}).get("Retry-After")
    if retry_after is not None:
        try:
            exc.retry_after_s = float(retry_after)
        except ValueError:
            pass  # HTTP-date form: ignore, the backoff schedule applies
    return exc


class InferAsyncRequest:
    """Handle returned by ``async_infer``; ``get_result()`` blocks for the result.

    Parity: tritonclient.http InferAsyncRequest (reference http/__init__.py:1683).
    """

    def __init__(self, future, verbose=False):
        self._future = future
        self._verbose = verbose

    def get_result(self, block=True, timeout=None):
        if not block and not self._future.done():
            raise_error("inference is not yet completed")
        try:
            return self._future.result(timeout=timeout)
        except InferenceServerException:
            raise
        except Exception as e:  # transport-level failure
            raise InferenceServerException(msg=str(e), debug_details=e) from e

    def cancel(self):
        return self._future.cancel()


class InferResult:
    """Parsed inference response: JSON header + sliced binary output section.

    Parity: reference http/__init__.py:2045-2168.
    """

    def __init__(self, response_header, binary_section, verbose=False):
        self._result = response_header
        self._verbose = verbose
        self._output_name_to_buffer = {}
        offset = 0
        for output in self._result.get("outputs", []):
            params = output.get("parameters", {})
            bin_size = params.get("binary_data_size")
            if bin_size is not None:
                self._output_name_to_buffer[output["name"]] = binary_section[
                    offset : offset + bin_size
                ]
                offset += bin_size

    @classmethod
    def from_response_body(
        cls, response_body, verbose=False, header_length=None, content_encoding=None
    ):
        body = _codec.decompress(bytes(response_body), content_encoding)
        header, binary = _codec.parse_infer_response_body(body, header_length)
        return cls(header, binary, verbose)

    def get_response(self):
        """The response header as a dict (JSON form of ModelInferResponse)."""
        return self._result

    def get_output(self, name):
        """The output's JSON metadata dict, or None if absent."""
        for output in self._result.get("outputs", []):
            if output["name"] == name:
                return output
        return None

    def as_numpy(self, name):
        """Output tensor as a numpy array (None if not present or in shm)."""
        output = self.get_output(name)
        if output is None:
            return None
        shape = output["shape"]
        datatype = output["datatype"]
        if name in self._output_name_to_buffer:
            return from_wire_bytes(
                self._output_name_to_buffer[name], datatype, shape
            )
        if "data" in output:
            return _np_from_json_data(output["data"], datatype, shape)
        return None


class InferenceServerClient:
    """Blocking HTTP client for every KServe-v2 endpoint.

    Parity: reference http/__init__.py:142-1510 (constructor args adapted:
    urllib3 pool instead of gevent; ``concurrency`` sizes both the connection
    pool and the async_infer worker pool).
    """

    def __init__(
        self,
        url,
        verbose=False,
        concurrency=1,
        connection_timeout=60.0,
        network_timeout=60.0,
        ssl=False,
        ssl_context=None,
        insecure=False,
        retry_policy=None,
        tracer=None,
        tenant=None,
    ):
        if "://" in url:
            scheme, _, rest = url.partition("://")
            if scheme not in ("http", "https"):
                raise_error(f"unsupported scheme '{scheme}' in url")
            url = rest
            ssl = ssl or scheme == "https"
        scheme = "https" if ssl else "http"
        self._base_url = f"{scheme}://{url}"
        self._endpoint = url  # host:port identity (trace attempt spans)
        self._verbose = verbose
        self._concurrency = concurrency
        pool_kwargs = {}
        if ssl:
            pool_kwargs["ssl_context"] = ssl_context
            if insecure:
                pool_kwargs["cert_reqs"] = "CERT_NONE"
                urllib3.disable_warnings()
        self._pool = urllib3.PoolManager(
            maxsize=max(1, concurrency),
            timeout=urllib3.Timeout(connect=connection_timeout, read=network_timeout),
            retries=False,
            **pool_kwargs,
        )
        # Opt-in resilience: a client_tpu.resilience.RetryPolicy routes
        # every request through retry/backoff/deadline/circuit-breaker.
        # None (the default) keeps the original single-attempt behavior.
        self._retry_policy = retry_policy
        # Opt-in tracing: a client_tpu.tracing.ClientTracer samples infer
        # calls, records client spans, and propagates a W3C traceparent so
        # the server's trace joins under the same trace id.
        self._tracer = tracer
        # Tenant identity: stamped as the x-tenant-id header on EVERY verb
        # so callers stop hand-threading headers= through each call (an
        # explicitly passed header still wins).
        self._tenant = None if tenant is None else str(tenant)
        self._executor = None  # lazily created for async_infer

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None
        self._pool.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- low-level request helpers -----------------------------------------

    def _request(self, method, uri, headers=None, query_params=None, body=None,
                 trace=None, client_timeout_s=None):
        if self._retry_policy is None:
            return self._attempt_once(
                method, uri, headers, query_params, body, client_timeout_s,
                trace,
            )

        def attempt(timeout_s):
            response = self._attempt_once(
                method, uri, headers, query_params, body,
                _resilience.combine_timeouts(timeout_s, client_timeout_s),
                trace,
            )
            # Overload statuses become exceptions so the retry loop sees
            # them (with the server's Retry-After hint attached); retries
            # exhausted -> the same exception _raise_if_error would build.
            if str(response.status) in self._retry_policy.retryable_statuses:
                raise _get_error_from_response(
                    response.data, response.status, response.headers
                )
            return response

        return _resilience.call_with_retry(attempt, self._retry_policy)

    def _attempt_once(self, method, uri, headers, query_params, body,
                      timeout_s, trace):
        """One transport attempt in a trace attempt span — retries show as
        repeated ATTEMPT_START/ATTEMPT_END pairs."""
        with _tracing.attempt_span(trace, endpoint=self._endpoint):
            return self._request_once(
                method, uri, headers, query_params, body, timeout_s
            )

    def _request_once(
        self, method, uri, headers=None, query_params=None, body=None, timeout_s=None
    ):
        headers = _stamp_tenant(headers, self._tenant)
        url = f"{self._base_url}/{uri}"
        if query_params:
            url += "?" + urlencode(query_params, doseq=True)
        if self._verbose:
            print(f"{method} {url}, headers {headers}")
        kwargs = {}
        if timeout_s is not None:  # deadline-derived per-attempt timeout
            kwargs["timeout"] = urllib3.Timeout(total=max(timeout_s, 1e-3))
        try:
            response = self._pool.request(
                method,
                url,
                body=body,
                headers=headers,
                preload_content=True,
                decode_content=False,
                **kwargs,
            )
        except InferenceServerException:
            raise
        except Exception as e:
            raise InferenceServerException(msg=str(e), debug_details=e) from e
        if self._verbose:
            print(response.status)
        return response

    def _get(self, uri, headers=None, query_params=None):
        return self._request("GET", uri, headers, query_params)

    def _post(self, uri, body=b"", headers=None, query_params=None):
        return self._request("POST", uri, headers, query_params, body=body)

    @staticmethod
    def _raise_if_error(response):
        if response.status != 200:
            raise _get_error_from_response(response.data, response.status)

    @staticmethod
    def _json_or_raise(response):
        InferenceServerClient._raise_if_error(response)
        content = _codec.decompress(
            response.data, response.headers.get("Content-Encoding")
        )
        return json.loads(content.decode("utf-8")) if content else {}

    # -- health -------------------------------------------------------------
    # Health verbs answer False on transport/connection errors instead of
    # raising (tritonclient reference semantics): an unreachable server IS
    # not-live/not-ready, and health probes must be safe to poll.  They
    # bypass the retry policy — a draining server's 503 readiness answer
    # is the answer, not a failure to retry through.

    def _probe(self, uri, headers, query_params):
        try:
            r = self._request_once("GET", uri, headers, query_params)
        except InferenceServerException:
            return False
        return r.status == 200

    def is_server_live(self, headers=None, query_params=None):
        return self._probe("v2/health/live", headers, query_params)

    def is_server_ready(self, headers=None, query_params=None):
        return self._probe("v2/health/ready", headers, query_params)

    def server_state(self, headers=None, query_params=None, timeout_s=None):
        """READY / NOT_READY / UNREACHABLE (client_tpu.utils constants).

        ``is_server_ready()`` collapses "answered not-ready" (draining) and
        "never answered" (dead) into False; this keeps them apart so a
        replica set can let a draining server finish its in-flight work
        while routing a dead one straight to its circuit breaker.
        ``timeout_s`` bounds the probe (background probers must not hang
        on a black-holed endpoint)."""
        try:
            r = self._request_once("GET", "v2/health/ready", headers,
                                   query_params, timeout_s=timeout_s)
        except InferenceServerException:
            return SERVER_UNREACHABLE
        return SERVER_READY if r.status == 200 else SERVER_NOT_READY

    def is_model_ready(self, model_name, model_version="", headers=None, query_params=None):
        uri = f"v2/models/{quote(model_name, safe='')}"
        if model_version:
            uri += f"/versions/{model_version}"
        return self._probe(uri + "/ready", headers, query_params)

    # -- metadata / config ---------------------------------------------------

    def get_server_metadata(self, headers=None, query_params=None):
        return self._json_or_raise(self._get("v2", headers, query_params))

    def get_model_metadata(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        uri = f"v2/models/{quote(model_name, safe='')}"
        if model_version:
            uri += f"/versions/{model_version}"
        return self._json_or_raise(self._get(uri, headers, query_params))

    def get_model_config(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        uri = f"v2/models/{quote(model_name, safe='')}"
        if model_version:
            uri += f"/versions/{model_version}"
        return self._json_or_raise(self._get(uri + "/config", headers, query_params))

    # -- repository ----------------------------------------------------------

    def get_model_repository_index(self, headers=None, query_params=None):
        return self._json_or_raise(
            self._post("v2/repository/index", b"", headers, query_params)
        )

    def load_model(
        self, model_name, headers=None, query_params=None, config=None, files=None
    ):
        body = {}
        if config is not None:
            body.setdefault("parameters", {})["config"] = (
                config if isinstance(config, str) else json.dumps(config)
            )
        if files:
            for path, content in files.items():
                body.setdefault("parameters", {})[path] = base64.b64encode(
                    content
                ).decode("utf-8")
        r = self._post(
            f"v2/repository/models/{quote(model_name, safe='')}/load",
            json.dumps(body).encode("utf-8") if body else b"",
            headers,
            query_params,
        )
        self._raise_if_error(r)

    def unload_model(
        self, model_name, headers=None, query_params=None, unload_dependents=False
    ):
        body = {"parameters": {"unload_dependents": unload_dependents}}
        r = self._post(
            f"v2/repository/models/{quote(model_name, safe='')}/unload",
            json.dumps(body).encode("utf-8"),
            headers,
            query_params,
        )
        self._raise_if_error(r)

    # -- statistics / trace / log -------------------------------------------

    def get_inference_statistics(
        self, model_name="", model_version="", headers=None, query_params=None
    ):
        if model_name:
            uri = f"v2/models/{quote(model_name, safe='')}"
            if model_version:
                uri += f"/versions/{model_version}"
            uri += "/stats"
        else:
            uri = "v2/models/stats"
        return self._json_or_raise(self._get(uri, headers, query_params))

    def update_trace_settings(
        self, model_name="", settings=None, headers=None, query_params=None
    ):
        uri = (
            f"v2/models/{quote(model_name, safe='')}/trace/setting"
            if model_name
            else "v2/trace/setting"
        )
        r = self._post(
            uri, json.dumps(settings or {}).encode("utf-8"), headers, query_params
        )
        return self._json_or_raise(r)

    def get_trace_settings(self, model_name="", headers=None, query_params=None):
        uri = (
            f"v2/models/{quote(model_name, safe='')}/trace/setting"
            if model_name
            else "v2/trace/setting"
        )
        return self._json_or_raise(self._get(uri, headers, query_params))

    def update_log_settings(self, settings, headers=None, query_params=None):
        r = self._post(
            "v2/logging", json.dumps(settings).encode("utf-8"), headers, query_params
        )
        return self._json_or_raise(r)

    def get_log_settings(self, headers=None, query_params=None):
        return self._json_or_raise(self._get("v2/logging", headers, query_params))

    # -- shared memory -------------------------------------------------------

    def _shm_status(self, kind, region_name, headers, query_params):
        uri = f"v2/{kind}"
        if region_name:
            uri += f"/region/{quote(region_name, safe='')}"
        uri += "/status"
        return self._json_or_raise(self._get(uri, headers, query_params))

    def get_system_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        return self._shm_status("systemsharedmemory", region_name, headers, query_params)

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, query_params=None
    ):
        body = json.dumps(
            {"key": key, "offset": offset, "byte_size": byte_size}
        ).encode("utf-8")
        r = self._post(
            f"v2/systemsharedmemory/region/{quote(name, safe='')}/register",
            body,
            headers,
            query_params,
        )
        self._raise_if_error(r)

    def unregister_system_shared_memory(
        self, name="", headers=None, query_params=None
    ):
        uri = "v2/systemsharedmemory"
        if name:
            uri += f"/region/{quote(name, safe='')}"
        uri += "/unregister"
        self._raise_if_error(self._post(uri, b"", headers, query_params))

    def get_cuda_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        return self._shm_status("cudasharedmemory", region_name, headers, query_params)

    def register_cuda_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, query_params=None
    ):
        body = json.dumps(
            {
                "raw_handle": {"b64": base64.b64encode(raw_handle).decode("utf-8")},
                "device_id": device_id,
                "byte_size": byte_size,
            }
        ).encode("utf-8")
        r = self._post(
            f"v2/cudasharedmemory/region/{quote(name, safe='')}/register",
            body,
            headers,
            query_params,
        )
        self._raise_if_error(r)

    def unregister_cuda_shared_memory(self, name="", headers=None, query_params=None):
        uri = "v2/cudasharedmemory"
        if name:
            uri += f"/region/{quote(name, safe='')}"
        uri += "/unregister"
        self._raise_if_error(self._post(uri, b"", headers, query_params))

    def get_tpu_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        return self._shm_status("tpusharedmemory", region_name, headers, query_params)

    def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, query_params=None
    ):
        """Register a TPU device-buffer region (client_tpu extension endpoint)."""
        body = json.dumps(
            {
                "raw_handle": {"b64": base64.b64encode(raw_handle).decode("utf-8")},
                "device_id": device_id,
                "byte_size": byte_size,
            }
        ).encode("utf-8")
        r = self._post(
            f"v2/tpusharedmemory/region/{quote(name, safe='')}/register",
            body,
            headers,
            query_params,
        )
        self._raise_if_error(r)

    def unregister_tpu_shared_memory(self, name="", headers=None, query_params=None):
        uri = "v2/tpusharedmemory"
        if name:
            uri += f"/region/{quote(name, safe='')}"
        uri += "/unregister"
        self._raise_if_error(self._post(uri, b"", headers, query_params))

    # -- inference -----------------------------------------------------------

    @staticmethod
    def generate_request_body(
        inputs,
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        parameters=None,
    ):
        """Build (body, json_size) without sending — the pipelining entry point
        (parity: reference http/__init__.py:1255)."""
        return _codec.build_infer_request_body(
            inputs,
            outputs,
            request_id,
            sequence_id,
            sequence_start,
            sequence_end,
            priority,
            timeout,
            parameters,
        )

    @staticmethod
    def parse_response_body(
        response_body, verbose=False, header_length=None, content_encoding=None
    ):
        """Parse a raw response body into InferResult (parity: reference
        http/__init__.py:1336)."""
        return InferResult.from_response_body(
            response_body, verbose, header_length, content_encoding
        )

    def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
        client_timeout_s=None,
    ):
        """Run one synchronous inference; returns InferResult.

        ``client_timeout_s`` bounds this request's transport time on the
        client side (the gRPC clients' ``client_timeout`` analog; distinct
        from ``timeout``, the KServe server-side budget in microseconds).
        With a retry policy it caps each attempt alongside the policy's
        deadline-derived budget."""
        with _tracing.client_span(self._tracer, model_name) as trace:
            body, json_size = _codec.build_infer_request_body(
                inputs,
                outputs,
                request_id,
                sequence_id,
                sequence_start,
                sequence_end,
                priority,
                timeout,
                parameters,
            )
            request_headers = dict(headers) if headers else {}
            if json_size is not None:
                request_headers["Inference-Header-Content-Length"] = str(json_size)
            body = _codec.compress(body, request_compression_algorithm)
            if request_compression_algorithm:
                request_headers["Content-Encoding"] = request_compression_algorithm
            if response_compression_algorithm:
                request_headers["Accept-Encoding"] = response_compression_algorithm
            if trace is not None:
                trace.event("CLIENT_SERIALIZE_END")
                request_headers["traceparent"] = trace.traceparent()

            uri = f"v2/models/{quote(model_name, safe='')}"
            if model_version:
                uri += f"/versions/{model_version}"
            uri += "/infer"
            response = self._request(
                "POST", uri, request_headers, query_params, body, trace=trace,
                client_timeout_s=client_timeout_s,
            )
            self._raise_if_error(response)
            header_length = response.headers.get(
                "Inference-Header-Content-Length"
            )
            return InferResult.from_response_body(
                response.data,
                self._verbose,
                int(header_length) if header_length is not None else None,
                response.headers.get("Content-Encoding"),
            )

    def async_infer(self, model_name, inputs, **kwargs):
        """Submit inference on the worker pool; returns InferAsyncRequest.

        Parity: reference http/__init__.py:1512 (gevent greenlet -> thread pool).
        """
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=max(1, self._concurrency),
                thread_name_prefix="client_tpu-http",
            )
        future = self._executor.submit(self.infer, model_name, inputs, **kwargs)
        return InferAsyncRequest(future, self._verbose)
