"""asyncio HTTP client — mirror of client_tpu.http over aiohttp.

Capability parity with ``tritonclient.http.aio`` (reference
src/python/library/tritonclient/http/aio/__init__.py:64-786).
"""

import asyncio
import base64
import json
from urllib.parse import quote

import aiohttp

from client_tpu import _codec
from client_tpu import resilience as _resilience
from client_tpu import tracing as _tracing
from client_tpu._infer_types import InferInput, InferRequestedOutput  # noqa: F401
from client_tpu.http import (  # same response/error parsing as sync
    InferResult,
    _get_error_from_response,
    _stamp_tenant,
)
from client_tpu.utils import (
    SERVER_NOT_READY,
    SERVER_READY,
    SERVER_UNREACHABLE,
    InferenceServerException,
    raise_error,
)

__all__ = [
    "InferenceServerClient",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
]


class InferenceServerClient:
    """asyncio client for every KServe-v2 HTTP endpoint."""

    def __init__(
        self,
        url,
        verbose=False,
        conn_limit=100,
        conn_timeout=60.0,
        ssl=False,
        ssl_context=None,
        retry_policy=None,
        tracer=None,
        tenant=None,
    ):
        if "://" in url:
            scheme, _, rest = url.partition("://")
            if scheme not in ("http", "https"):
                raise_error(f"unsupported scheme '{scheme}' in url")
            url = rest
            ssl = ssl or scheme == "https"
        self._base_url = f"{'https' if ssl else 'http'}://{url}"
        self._endpoint = url  # host:port identity (trace attempt spans)
        self._verbose = verbose
        connector = aiohttp.TCPConnector(limit=conn_limit, ssl=ssl_context if ssl else False)
        self._session = aiohttp.ClientSession(
            connector=connector,
            timeout=aiohttp.ClientTimeout(total=conn_timeout),
            auto_decompress=False,
        )
        # Opt-in resilience (client_tpu.resilience.RetryPolicy); None keeps
        # the original single-attempt behavior.
        self._retry_policy = retry_policy
        # Opt-in tracing (client_tpu.tracing.ClientTracer): client spans +
        # traceparent propagation, same semantics as the sync client.
        self._tracer = tracer
        # Tenant identity stamped on every verb (sync-client semantics).
        self._tenant = None if tenant is None else str(tenant)

    async def close(self):
        await self._session.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    async def _get(self, uri, headers=None, query_params=None):
        return await self._request("GET", uri, headers, query_params)

    async def _post(self, uri, body=b"", headers=None, query_params=None):
        return await self._request("POST", uri, headers, query_params, body)

    async def _request(self, method, uri, headers=None, query_params=None,
                       body=b"", trace=None, client_timeout_s=None):
        if self._retry_policy is None:
            return await self._attempt_once(
                method, uri, headers, query_params, body, client_timeout_s,
                trace,
            )

        async def attempt(timeout_s):
            response = await self._attempt_once(
                method, uri, headers, query_params, body,
                _resilience.combine_timeouts(timeout_s, client_timeout_s),
                trace,
            )
            # Overload statuses become exceptions for the retry loop (with
            # the Retry-After hint); the body read happens inside the
            # attempt so a mid-body truncation is retried too (aiohttp
            # caches it, later read() calls return the same bytes).
            if str(response.status) in self._retry_policy.retryable_statuses:
                raise await self._error_from_response(response)
            await response.read()
            return response

        return await _resilience.acall_with_retry(attempt, self._retry_policy)

    async def _attempt_once(self, method, uri, headers, query_params, body,
                            timeout_s, trace):
        """One transport attempt in a trace attempt span — retries show as
        repeated ATTEMPT_START/ATTEMPT_END pairs."""
        with _tracing.attempt_span(trace, endpoint=self._endpoint):
            return await self._request_once(
                method, uri, headers, query_params, body, timeout_s
            )

    async def _request_once(
        self, method, uri, headers=None, query_params=None, body=b"", timeout_s=None
    ):
        headers = _stamp_tenant(headers, self._tenant)
        if self._verbose:
            print(f"{method} {self._base_url}/{uri}")
        kwargs = {}
        if timeout_s is not None:  # deadline-derived per-attempt timeout
            kwargs["timeout"] = aiohttp.ClientTimeout(total=max(timeout_s, 1e-3))
        if method == "GET":
            return await self._session.get(
                f"{self._base_url}/{uri}", headers=headers, params=query_params,
                **kwargs,
            )
        return await self._session.post(
            f"{self._base_url}/{uri}", data=body, headers=headers,
            params=query_params, **kwargs,
        )

    @staticmethod
    async def _error_from_response(response):
        body = await response.read()
        # same error extraction + Retry-After parsing as the sync client
        return _get_error_from_response(body, response.status, response.headers)

    @classmethod
    async def _raise_if_error(cls, response):
        if response.status != 200:
            raise await cls._error_from_response(response)

    @staticmethod
    async def _json_or_raise(response):
        await InferenceServerClient._raise_if_error(response)
        body = _codec.decompress(
            await response.read(), response.headers.get("Content-Encoding")
        )
        return json.loads(body.decode("utf-8")) if body else {}

    # -- health --------------------------------------------------------------
    # Health verbs answer False on transport/connection errors instead of
    # raising (tritonclient reference semantics): health probes must be
    # safe to poll against a down server.  They bypass the retry policy —
    # a draining server's 503 readiness answer is the answer.

    _HEALTH_ERRORS = (
        InferenceServerException,
        aiohttp.ClientError,
        asyncio.TimeoutError,
        OSError,
    )

    async def _probe(self, uri, headers, query_params):
        try:
            r = await self._request_once("GET", uri, headers, query_params)
        except self._HEALTH_ERRORS:
            return False
        return r.status == 200

    async def is_server_live(self, headers=None, query_params=None):
        return await self._probe("v2/health/live", headers, query_params)

    async def is_server_ready(self, headers=None, query_params=None):
        return await self._probe("v2/health/ready", headers, query_params)

    async def server_state(self, headers=None, query_params=None,
                           timeout_s=None):
        """READY / NOT_READY / UNREACHABLE (client_tpu.utils constants) —
        distinguishes a draining server (answered not-ready) from a dead
        one (never answered); same contract as the sync client.
        ``timeout_s`` bounds the probe."""
        try:
            r = await self._request_once(
                "GET", "v2/health/ready", headers, query_params,
                timeout_s=timeout_s,
            )
        except self._HEALTH_ERRORS:
            return SERVER_UNREACHABLE
        return SERVER_READY if r.status == 200 else SERVER_NOT_READY

    async def is_model_ready(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        uri = f"v2/models/{quote(model_name, safe='')}"
        if model_version:
            uri += f"/versions/{model_version}"
        return await self._probe(uri + "/ready", headers, query_params)

    # -- metadata / config / repository --------------------------------------

    async def get_server_metadata(self, headers=None, query_params=None):
        return await self._json_or_raise(await self._get("v2", headers, query_params))

    async def get_model_metadata(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        uri = f"v2/models/{quote(model_name, safe='')}"
        if model_version:
            uri += f"/versions/{model_version}"
        return await self._json_or_raise(await self._get(uri, headers, query_params))

    async def get_model_config(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        uri = f"v2/models/{quote(model_name, safe='')}"
        if model_version:
            uri += f"/versions/{model_version}"
        return await self._json_or_raise(
            await self._get(uri + "/config", headers, query_params)
        )

    async def get_model_repository_index(self, headers=None, query_params=None):
        return await self._json_or_raise(
            await self._post("v2/repository/index", b"", headers, query_params)
        )

    async def load_model(
        self, model_name, headers=None, query_params=None, config=None, files=None
    ):
        body = {}
        if config is not None:
            body.setdefault("parameters", {})["config"] = (
                config if isinstance(config, str) else json.dumps(config)
            )
        for path, content in (files or {}).items():
            body.setdefault("parameters", {})[path] = base64.b64encode(content).decode()
        r = await self._post(
            f"v2/repository/models/{quote(model_name, safe='')}/load",
            json.dumps(body).encode() if body else b"",
            headers,
            query_params,
        )
        await self._raise_if_error(r)

    async def unload_model(
        self, model_name, headers=None, query_params=None, unload_dependents=False
    ):
        r = await self._post(
            f"v2/repository/models/{quote(model_name, safe='')}/unload",
            json.dumps({"parameters": {"unload_dependents": unload_dependents}}).encode(),
            headers,
            query_params,
        )
        await self._raise_if_error(r)

    # -- statistics ----------------------------------------------------------

    async def get_inference_statistics(
        self, model_name="", model_version="", headers=None, query_params=None
    ):
        if model_name:
            uri = f"v2/models/{quote(model_name, safe='')}"
            if model_version:
                uri += f"/versions/{model_version}"
            uri += "/stats"
        else:
            uri = "v2/models/stats"
        return await self._json_or_raise(await self._get(uri, headers, query_params))

    # -- trace / log settings (parity with the sync client) ------------------

    async def update_trace_settings(
        self, model_name="", settings=None, headers=None, query_params=None
    ):
        uri = (
            f"v2/models/{quote(model_name, safe='')}/trace/setting"
            if model_name
            else "v2/trace/setting"
        )
        r = await self._post(
            uri, json.dumps(settings or {}).encode("utf-8"), headers,
            query_params,
        )
        return await self._json_or_raise(r)

    async def get_trace_settings(
        self, model_name="", headers=None, query_params=None
    ):
        uri = (
            f"v2/models/{quote(model_name, safe='')}/trace/setting"
            if model_name
            else "v2/trace/setting"
        )
        return await self._json_or_raise(
            await self._get(uri, headers, query_params)
        )

    async def update_log_settings(
        self, settings, headers=None, query_params=None
    ):
        r = await self._post(
            "v2/logging", json.dumps(settings).encode("utf-8"), headers,
            query_params,
        )
        return await self._json_or_raise(r)

    async def get_log_settings(self, headers=None, query_params=None):
        return await self._json_or_raise(
            await self._get("v2/logging", headers, query_params)
        )

    # -- pipelining statics (reference http/__init__.py:1255/1336; the bodies
    #    are transport-independent, shared with the sync client) -------------

    @staticmethod
    def generate_request_body(
        inputs, outputs=None, request_id="", sequence_id=0,
        sequence_start=False, sequence_end=False, priority=0, timeout=None,
        parameters=None,
    ):
        """Build (body, json_size) without sending."""
        return _codec.build_infer_request_body(
            inputs, outputs, request_id, sequence_id, sequence_start,
            sequence_end, priority, timeout, parameters,
        )

    @staticmethod
    def parse_response_body(
        response_body, verbose=False, header_length=None,
        content_encoding=None,
    ):
        """Parse a raw response body into InferResult."""
        return InferResult.from_response_body(
            response_body, verbose, header_length, content_encoding
        )

    # -- shared memory -------------------------------------------------------

    async def get_system_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        uri = "v2/systemsharedmemory"
        if region_name:
            uri += f"/region/{quote(region_name, safe='')}"
        return await self._json_or_raise(
            await self._get(uri + "/status", headers, query_params)
        )

    async def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, query_params=None
    ):
        r = await self._post(
            f"v2/systemsharedmemory/region/{quote(name, safe='')}/register",
            json.dumps({"key": key, "offset": offset, "byte_size": byte_size}).encode(),
            headers,
            query_params,
        )
        await self._raise_if_error(r)

    async def unregister_system_shared_memory(
        self, name="", headers=None, query_params=None
    ):
        uri = "v2/systemsharedmemory"
        if name:
            uri += f"/region/{quote(name, safe='')}"
        r = await self._post(uri + "/unregister", b"", headers, query_params)
        await self._raise_if_error(r)

    async def get_tpu_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        uri = "v2/tpusharedmemory"
        if region_name:
            uri += f"/region/{quote(region_name, safe='')}"
        return await self._json_or_raise(
            await self._get(uri + "/status", headers, query_params)
        )

    async def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, query_params=None
    ):
        r = await self._post(
            f"v2/tpusharedmemory/region/{quote(name, safe='')}/register",
            json.dumps(
                {
                    "raw_handle": {"b64": base64.b64encode(raw_handle).decode()},
                    "device_id": device_id,
                    "byte_size": byte_size,
                }
            ).encode(),
            headers,
            query_params,
        )
        await self._raise_if_error(r)

    async def unregister_tpu_shared_memory(
        self, name="", headers=None, query_params=None
    ):
        uri = "v2/tpusharedmemory"
        if name:
            uri += f"/region/{quote(name, safe='')}"
        r = await self._post(uri + "/unregister", b"", headers, query_params)
        await self._raise_if_error(r)

    # -- inference -----------------------------------------------------------

    async def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
        client_timeout_s=None,
    ):
        with _tracing.client_span(self._tracer, model_name) as trace:
            body, json_size = _codec.build_infer_request_body(
                inputs,
                outputs,
                request_id,
                sequence_id,
                sequence_start,
                sequence_end,
                priority,
                timeout,
                parameters,
            )
            request_headers = dict(headers) if headers else {}
            if json_size is not None:
                request_headers["Inference-Header-Content-Length"] = str(json_size)
            body = _codec.compress(body, request_compression_algorithm)
            if request_compression_algorithm:
                request_headers["Content-Encoding"] = request_compression_algorithm
            if response_compression_algorithm:
                request_headers["Accept-Encoding"] = response_compression_algorithm
            if trace is not None:
                trace.event("CLIENT_SERIALIZE_END")
                request_headers["traceparent"] = trace.traceparent()
            uri = f"v2/models/{quote(model_name, safe='')}"
            if model_version:
                uri += f"/versions/{model_version}"
            uri += "/infer"
            response = await self._request(
                "POST", uri, request_headers, query_params, body, trace=trace,
                client_timeout_s=client_timeout_s,
            )
            await self._raise_if_error(response)
            data = await response.read()
            header_length = response.headers.get(
                "Inference-Header-Content-Length"
            )
            return InferResult.from_response_body(
                data,
                self._verbose,
                int(header_length) if header_length is not None else None,
                response.headers.get("Content-Encoding"),
            )
