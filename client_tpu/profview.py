"""profview: render continuous-profiler reports as top-down
attribution tables (sibling of traceview, which renders per-trace
timelines — this renders *where the engine's time goes*).

Input is either a prof report JSON file (the ``GET /v2/debug/prof``
payload — ``{"kind": "prof_report", "engines": [rollups...]}`` — or a
single engine rollup) or a flight-recorder JSON-lines dump whose
``prof_tick`` records profview re-rolls into the same shape::

    curl :8000/v2/debug/prof > prof.json
    python -m client_tpu.profview prof.json
    python -m client_tpu.profview --format json flight-*.jsonl
    python -m client_tpu.profview --live          # self-contained demo

Per engine it prints tick counts by kind, the ranked per-phase table
(seconds + percentage of covered time), the dispatch/compute/host/idle
attribution split, and per-model device share / MFU — the table the
38%-idle-link question is answered from.

``--live`` spins an in-process engine (the cnn224 headline model), runs
a short unary workload through it, and renders its own report — the
``make prof`` target; no server or file needed.

Exit codes: 0 rendered, 1 no prof data in the inputs, 2 unreadable or
unparsable input.  Everything here is stdlib + the serve package.
"""

import argparse
import json
import sys

from client_tpu.serve.prof import attribute_phases

__all__ = ["load_reports", "rollup_from_ticks", "render_engine", "main"]


def _engines_of(obj):
    """Engine rollup dicts inside one parsed JSON object (a prof_report,
    a bare rollup, or a bench record carrying a ``prof`` block)."""
    if not isinstance(obj, dict):
        return []
    if isinstance(obj.get("engines"), list):
        return [e for e in obj["engines"] if isinstance(e, dict)]
    if "phases" in obj and "kinds" in obj:
        return [obj]
    return []


def rollup_from_ticks(ticks):
    """Re-roll flight-dump ``prof_tick`` records into per-engine rollup
    dicts (the ring's aggregation replayed offline; MFU needs the live
    profiler's FLOP totals, so it is absent here)."""
    by_engine = {}
    for record in ticks:
        engine = str(record.get("engine", ""))
        by_engine.setdefault(engine, []).append(record)
    rollups = []
    for engine, records in sorted(by_engine.items()):
        phases = {}
        kinds = {}
        models = {}
        wall = 0.0
        ticks_n = 0
        for record in records:
            ticks_n += record.get("ticks", 1)
            wall += float(record.get("dur_s", 0.0))
            kind = str(record.get("tick_kind") or record.get("kind"))
            kinds[kind] = kinds.get(kind, 0) + record.get("ticks", 1)
            for name, seconds in (record.get("phases") or {}).items():
                phases[name] = phases.get(name, 0.0) + float(seconds)
            model = record.get("model")
            if model is not None:
                entry = models.setdefault(str(model), [0.0, 0])
                entry[1] += int(record.get("items", 0))
        covered = sum(phases.values())
        rollups.append({
            "engine": engine,
            "ticks": ticks_n,
            "wall_s": round(wall, 6),
            "covered_s": round(covered, 6),
            "kinds": kinds,
            "phases": {
                name: {
                    "s": round(seconds, 6),
                    "pct": round(100.0 * seconds / covered, 2)
                    if covered else 0.0,
                }
                for name, seconds in sorted(
                    phases.items(), key=lambda kv: -kv[1]
                )
            },
            "models": {
                m: {"device_s": 0.0, "items": v[1],
                    "compute_share_pct": 0.0}
                for m, v in sorted(models.items())
            },
            "attribution": attribute_phases(phases, wall_s=wall),
        })
    return rollups


def load_reports(paths):
    """Engine rollups from *paths*: prof report JSON files and/or
    flight JSON-lines dumps.  Unreadable files and garbage JSON raise —
    a postmortem artifact that does not parse should be loud."""
    engines = []
    ticks = []
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        try:
            obj = json.loads(text)
        except ValueError:
            obj = None
        if obj is not None:
            engines.extend(_engines_of(obj))
            if isinstance(obj, dict) and "prof" in obj:
                engines.extend(_engines_of(obj["prof"]))
            continue
        # JSON-lines (a flight dump): collect its prof_tick records
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            record = json.loads(line)
            if isinstance(record, dict):
                if record.get("kind") == "prof_tick":
                    ticks.append(record)
                else:
                    engines.extend(_engines_of(record))
    engines.extend(rollup_from_ticks(ticks))
    return engines


def render_engine(rollup, out):
    """Human attribution table for one engine's rollup."""
    kinds = rollup.get("kinds") or {}
    kinds_txt = " ".join(
        f"{k}={v}" for k, v in sorted(kinds.items(), key=lambda kv: -kv[1])
    )
    out.write(
        f"engine {rollup.get('engine') or '-'}  "
        f"ticks={rollup.get('ticks', 0)} "
        f"wall={rollup.get('wall_s', 0.0):.3f}s "
        f"covered={rollup.get('covered_s', 0.0):.3f}s"
        + (f"  [{kinds_txt}]" if kinds_txt else "")
        + "\n"
    )
    attribution = rollup.get("attribution")
    if attribution:
        out.write(
            "  attribution: "
            + " | ".join(
                f"{key[:-4]} {attribution[key]:.1f}%"
                for key in ("compute_pct", "dispatch_pct", "host_pct",
                            "idle_pct")
                if key in attribution
            )
            + "\n"
        )
    for name, row in (rollup.get("phases") or {}).items():
        out.write(
            f"    {name:<18} {row['s']:>10.4f}s  {row['pct']:>6.2f}%\n"
        )
    for model, row in (rollup.get("models") or {}).items():
        bits = [
            f"    model {model:<12} items={row.get('items', 0)}",
            f"device={row.get('device_s', 0.0):.4f}s",
            f"share={row.get('compute_share_pct', 0.0):.1f}%",
        ]
        if row.get("mfu_pct") is not None:
            bits.append(f"mfu={row['mfu_pct']:.3f}%")
        out.write(" ".join(bits) + "\n")


def live_report(requests=64, image_size=64):
    """Spin an in-process engine, run a short cnn unary workload, and
    return its prof report — the ``--live`` / ``make prof`` path (no
    server, no files; small images keep it a few seconds on CPU)."""
    import numpy as np

    from client_tpu.serve.model_runtime import InferenceEngine
    from client_tpu.serve.models.vision import cnn_classifier_model
    from client_tpu.utils import to_wire_bytes

    engine = InferenceEngine(
        models=[cnn_classifier_model(image_size=image_size)]
    )
    try:
        arr = np.zeros((1, 3, image_size, image_size), np.float32)
        raw = to_wire_bytes(arr, "FP32")
        request = {
            "id": "",
            "inputs": [{
                "name": "INPUT0",
                "datatype": "FP32",
                "shape": list(arr.shape),
                "parameters": {"binary_data_size": len(raw)},
            }],
            "outputs": [
                {"name": "OUTPUT0", "parameters": {"binary_data": True}}
            ],
        }
        for _ in range(int(requests)):
            engine.execute("cnn_classifier", "", dict(request), raw)
        return engine.prof.report(window_s=0)
    finally:
        engine.close()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m client_tpu.profview",
        description="Render continuous-profiler reports "
                    "(/v2/debug/prof JSON or flight dumps) as top-down "
                    "time-attribution tables.",
    )
    parser.add_argument(
        "files", nargs="*",
        help="prof report JSON and/or flight JSON-lines files",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="text tables (default) or one JSON rollup per engine",
    )
    parser.add_argument(
        "--engine", default=None,
        help="only engines whose name starts with this",
    )
    parser.add_argument(
        "--live", action="store_true",
        help="ignore files: run a short in-process cnn workload and "
             "render its own report (the `make prof` path)",
    )
    args = parser.parse_args(argv)
    if args.live:
        engines = live_report().get("engines", [])
    else:
        if not args.files:
            parser.error("give prof/flight files or --live")
        try:
            engines = load_reports(args.files)
        except (OSError, ValueError) as e:
            print(f"profview: {e}", file=sys.stderr)
            return 2
    if args.engine is not None:
        engines = [
            e for e in engines
            if str(e.get("engine", "")).startswith(args.engine)
        ]
    engines = [e for e in engines if e.get("ticks")]
    if not engines:
        print("no prof data found", file=sys.stderr)
        return 1
    if args.format == "json":
        for rollup in engines:
            sys.stdout.write(
                json.dumps(rollup, separators=(",", ":")) + "\n"
            )
        return 0
    for rollup in engines:
        render_engine(rollup, sys.stdout)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
