"""Int8 weight-only quantization with a Pallas dequant-matmul kernel.

Weights quantize per-output-channel symmetric (int8 value × f32 scale); the
kernel streams int8 weight tiles HBM→VMEM (half the DMA of bf16), runs the
matmul with f32 accumulation over the K grid axis in VMEM scratch, and
applies the channel scales once at the end — activations stay unquantized,
so there is no activation calibration to manage.

What it buys, measured on a v5e chip: ~1.8× smaller serving weights (the
capacity to hold a ~2× larger model per chip), greedy decode that agrees
with bf16, and identical per-step device time at sub-GB model sizes — at
that scale decode is dispatch-bound, not HBM-bound, so the bandwidth win
only turns into a latency win for weight footprints approaching the HBM
bandwidth × step-time product.

Grid (M tiles, N tiles, K tiles), K innermost/sequential — the same
streamed-accumulator shape as client_tpu.ops.flash_attention.  Off-TPU the
kernel runs in interpret mode, so CPU tests exercise the chip's code path.

The reference stack has no quantization anywhere; this is a TPU-serving
capability addition (pallas guide §"Quantization Kernels" pattern).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from client_tpu._jax_compat import CompilerParams as _CompilerParams


def quantize_int8(w):
    """Per-output-channel symmetric int8 quantization of a [K, N] weight.

    Returns {"q": int8 [K, N], "s": f32 [N]} with w ≈ q * s.
    """
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=0)  # per output channel
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def is_quantized(w):
    return isinstance(w, dict) and "q" in w and "s" in w


def _int8_mm_kernel(x_ref, wq_ref, s_ref, o_ref, acc_ref, *, n_k):
    """One (m-tile, n-tile, k-tile) program; f32 accumulator in scratch."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # dequant (sans scale) into x's dtype: bf16 holds all int8 values
    # exactly, and the dot then runs at bf16 MXU rate with f32 accumulation
    x = x_ref[...]                              # [bm, bk]
    w = wq_ref[...].astype(x.dtype)             # [bk, bn]
    acc_ref[:] += lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(ki == n_k - 1)
    def _finish():
        # channel scales applied once after the K accumulation
        o_ref[...] = (acc_ref[:] * s_ref[...]).astype(o_ref.dtype)


def int8_matmul(x, qw, block_m=128, block_n=128, block_k=512,
                interpret=None):
    """``x @ (q * s)`` with int8 weight tiles streamed through VMEM.

    Args:
      x: [..., K] activations (any float dtype; leading dims fold into M).
      qw: dict from :func:`quantize_int8` ({"q": int8 [K, N], "s": f32 [N]}).

    Returns [..., N] in x's dtype.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q, s = qw["q"], qw["s"]
    k, n = q.shape
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, k)

    # tile sizes: sublane/lane-aligned, clamped to padded dims
    bm = min(block_m, max(8, -(-m // 8) * 8))
    bn = min(block_n, n)
    bk = min(block_k, k)
    pad_m = (-m) % bm
    if n % bn or k % bk:
        # ragged weight dims: dequantized jnp fallback (rare — projection
        # widths are MXU-shaped multiples in every shipped config)
        w = q.astype(x.dtype) * s.astype(x.dtype)
        return (x2[:m] @ w).reshape(*lead, n)
    if pad_m:
        x2 = jnp.pad(x2, ((0, pad_m), (0, 0)))

    grid = ((m + pad_m) // bm, n // bn, k // bk)
    out = pl.pallas_call(
        functools.partial(_int8_mm_kernel, n_k=grid[2]),
        out_shape=jax.ShapeDtypeStruct((m + pad_m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x2, q, s.reshape(1, n))
    if pad_m:
        out = out[:m]
    return out.reshape(*lead, n)


def matmul(x, w, **kwargs):
    """Dispatch helper: plain ``x @ w`` or the int8 kernel for quantized w."""
    if is_quantized(w):
        return int8_matmul(x, w, **kwargs)
    return x @ w
