"""Causal flash attention as a Pallas TPU kernel.

Grid (batch·head, Q blocks, KV blocks): the KV dimension is the innermost,
sequentially-iterated ("arbitrary") grid axis, so only ONE [Bk, D] K block
and V block are VMEM-resident at a time — Pallas double-buffers the block
DMAs while the streaming-softmax state (running max / denominator /
f32 accumulator) persists in VMEM scratch across the KV sweep.  VMEM use is
O(Bq·D + Bk·D) regardless of sequence length, so the kernel compiles at any
T the HBM can hold; the [T, T] score matrix never exists anywhere.  Causal
masking skips the compute (not just the scores) of fully-past-diagonal
blocks via ``pl.when``.  MXU work is the two block matmuls (Q·Kᵀ, P·V),
accumulated f32.

Backward: ``jax.custom_vjp`` whose bwd recomputes attention with the plain
einsum formulation and differentiates that — the forward keeps flash memory
behavior (nothing saved but q/k/v), the backward trades the O(T²) score
materialization back in.  A fused Pallas backward is the next optimization.

Off-TPU (CPU tests, the 8-device virtual mesh) the kernel runs in Pallas
interpret mode automatically, so every test exercises the same code path
the chip runs compiled.

Reference has no analog (client-only stack); this implements the standard
flash-attention-2 forward on the layout conventions of
client_tpu.parallel.ring_attention (same [B, T, H, D] interface as
``plain_attention``).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # -inf stand-in that keeps exp() NaN-free


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
               scale, block_q, block_k, causal):
    """One (batch·head, q-block, kv-block) program.

    Block shapes: q_ref/o_ref [1, block_q, D]; k_ref/v_ref [1, block_k, D].
    acc/m/l scratch persists across the (sequential) KV grid axis.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    # a KV block strictly past this Q block's last row contributes nothing —
    # skip its matmuls entirely
    diag_ok = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(diag_ok)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale  # [Bq, D]
        kb = k_ref[0].astype(jnp.float32)         # [Bk, D]
        vb = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Bq, Bk]
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0
            )
            kv_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1
            )
            s = jnp.where(q_pos >= kv_pos, s, _NEG)
        m = m_ref[:]
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = new_m

    @pl.when(ki == n_kv - 1)
    def _finish():
        # every real row saw at least its own diagonal key, so l > 0; the
        # guard only shields padded Q rows, whose output is sliced off
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        ).astype(o_ref.dtype)


def _fa_forward(q, k, v, scale, block_q, block_k, causal, interpret):
    """[BH, T, D] inputs → [BH, T, D] output via the Pallas kernel."""
    bh, t, d = q.shape
    grid = (bh, t // block_q, t // block_k)
    kernel = functools.partial(
        _fa_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


def _reference(q, k, v, causal, scale):
    """Plain einsum attention on [BH, T, D] — the bwd recompute path."""
    s = jnp.einsum(
        "bqd,bkd->bqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        s = jnp.where(mask[None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fa(q, k, v, scale, block_q, block_k, causal, interpret):
    return _fa_forward(q, k, v, scale, block_q, block_k, causal, interpret)


def _fa_fwd(q, k, v, scale, block_q, block_k, causal, interpret):
    out = _fa_forward(q, k, v, scale, block_q, block_k, causal, interpret)
    return out, (q, k, v)


def _fa_bwd(scale, block_q, block_k, causal, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _reference(a, b, c, causal, scale),
                     q, k, v)
    return vjp(g)


_fa.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(q, k, v, causal=True, scale=None, block_q=128,
                    block_k=128, interpret=None):
    """Flash attention with the ``plain_attention`` interface.

    Args:
      q, k, v: [B, T, H, D] (same head count — repeat GQA KV first, as the
        transformer's attention block already does).
      causal: apply the causal mask (q and kv must be the same length).
      scale: score scale; defaults to D**-0.5.
      block_q, block_k: kernel tile sizes (clamped to the padded length).
      interpret: force Pallas interpret mode; default: on for any backend
        without a real TPU.

    Returns [B, T, H, D] in q's dtype.
    """
    b, t, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # Blocks must stay sublane-aligned (Mosaic tiling: the second-to-last
    # dim of a VMEM access needs 8/16/32-multiples by dtype) — so never
    # clamp a block to a ragged t; round t up and pad instead.
    align = 32
    block_q = min(block_q, -(-t // align) * align)
    block_k = min(block_k, -(-t // align) * align)
    # padded length must tile by BOTH block sizes
    pad = (-t) % math.lcm(block_q, block_k)

    if pad and not causal:
        # non-causal has no positional mask to neutralize padded keys; the
        # ragged remainder is small — use the plain formulation directly
        from client_tpu.parallel.ring_attention import plain_attention

        return plain_attention(q, k, v, causal=False, scale=scale)

    def fold(x):
        # [B,T,H,D] -> [B*H, T, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    if pad:
        # padded KV rows sit in the causal future of every real Q row (the
        # position mask zeroes them); padded Q rows are sliced off below
        qf = jnp.pad(qf, ((0, 0), (0, pad), (0, 0)))
        kf = jnp.pad(kf, ((0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad), (0, 0)))

    out = _fa(qf, kf, vf, scale, block_q, block_k, causal, interpret)
    if pad:
        out = out[:, :t]
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
