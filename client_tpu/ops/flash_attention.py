"""Causal flash attention as Pallas TPU kernels (forward + fused backward).

Forward — grid (batch·head, Q blocks, KV blocks): the KV dimension is the
innermost, sequentially-iterated ("arbitrary") grid axis, so only ONE
[Bk, D] K block and V block are VMEM-resident at a time — Pallas
double-buffers the block DMAs while the streaming-softmax state (running
max / denominator / f32 accumulator) persists in VMEM scratch across the KV
sweep.  VMEM use is O(Bq·D + Bk·D) regardless of sequence length, so the
kernel compiles at any T the HBM can hold; the [T, T] score matrix never
exists anywhere.  Causal masking skips the compute (not just the scores) of
fully-past-diagonal blocks via ``pl.when``.  Alongside the output the
forward emits the per-row log-sum-exp, the one O(T) residual the backward
needs.

Backward — the standard two-kernel flash-attention-2 scheme, both streaming
the same way as the forward:
- dQ kernel: grid (BH, Q blocks, KV blocks), dQ accumulated in VMEM
  scratch across the KV sweep; scores recomputed blockwise from q/k and the
  saved LSE (p = exp(s − lse)), never materialized globally.
- dK/dV kernel: grid (BH, KV blocks, Q blocks), dK and dV accumulated in
  scratch across the Q sweep.
Both use delta = rowsum(dO ⊙ O) (computed once, O(T)) for the softmax
Jacobian, so memory stays O(block) end to end — no O(T²) anywhere in
training either.

Off-TPU (CPU tests, the 8-device virtual mesh) the kernels run in Pallas
interpret mode automatically, so every test exercises the same code path
the chip runs compiled.

Reference has no analog (client-only stack); layout conventions follow
client_tpu.parallel.ring_attention (same [B, T, H, D] interface as
``plain_attention``).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from client_tpu._jax_compat import CompilerParams as _CompilerParams

_NEG = -1e30  # -inf stand-in that keeps exp() NaN-free


def _block_scores(q_ref, k_ref, qi, ki, scale, block_q, block_k, causal):
    """Recompute one [Bq, Bk] score block (f32, scaled, causally masked)."""
    q = q_ref[0].astype(jnp.float32)
    kb = k_ref[0].astype(jnp.float32)
    s = lax.dot_general(
        q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0
        )
        kv_pos = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        s = jnp.where(q_pos >= kv_pos, s, _NEG)
    return s


def _block_dscores(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref,
                   qi, ki, scale, block_q, block_k, causal):
    """Backward softmax-Jacobian for one block pair: returns (p, ds, do32).

    p = exp(s − lse) recomputed from the saved LSE;
    ds = p·(dO·Vᵀ − delta + dLSE)·scale — the dLSE term carries the
    cotangent of the forward's log-sum-exp output (∂lse_i/∂s_ij = p_ij),
    zero when only the attention output is differentiated.  Shared verbatim
    by the dQ and dK/dV kernels.
    """
    s = _block_scores(q_ref, k_ref, qi, ki, scale, block_q, block_k, causal)
    p = jnp.exp(s - lse_ref[...].reshape(-1, 1))  # [Bq, Bk]
    do = do_ref[0].astype(jnp.float32)
    vb = v_ref[0].astype(jnp.float32)
    dp = lax.dot_general(
        do, vb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [Bq, Bk]
    row = (dp - delta_ref[...].reshape(-1, 1)
           + glse_ref[...].reshape(-1, 1))
    ds = p * row * scale
    return p, ds, do


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
               scale, block_q, block_k, causal):
    """Forward: one (batch·head, q-block, kv-block) program.

    Block shapes: q_ref/o_ref [1, block_q, D]; k_ref/v_ref [1, block_k, D];
    lse_ref [1, block_q, 1] (trailing singleton keeps the block 2D-tileable
    on TPU).  acc/m/l scratch persists across the KV axis.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    # a KV block strictly past this Q block's last row contributes nothing —
    # skip its matmuls entirely
    diag_ok = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(diag_ok)
    def _accumulate():
        s = _block_scores(q_ref, k_ref, qi, ki, scale, block_q, block_k,
                          causal)
        vb = v_ref[0].astype(jnp.float32)
        m = m_ref[:]
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = new_m

    @pl.when(ki == n_kv - 1)
    def _finish():
        # every real row saw at least its own diagonal key, so l > 0; the
        # guard only shields padded Q rows, whose output is sliced off
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[...] = (m_ref[:] + jnp.log(l)).reshape(1, -1, 1)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the varying-mesh-axes of ``like`` so
    pallas_call outputs type-check under shard_map's check_vma."""
    vma = tuple(jax.typeof(like).vma) if hasattr(jax, "typeof") else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def _fa_forward(q, k, v, scale, block_q, block_k, causal, interpret):
    """[BH, T, D] inputs → ([BH, T, D] out, [BH, T, 1] lse)."""
    bh, t, d = q.shape
    grid = (bh, t // block_q, t // block_k)
    kernel = functools.partial(
        _fa_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal,
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            _sds((bh, t, d), q.dtype, q),
            _sds((bh, t, 1), jnp.float32, q),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref,
               dq_ref, acc_ref, *, scale, block_q, block_k, causal):
    """dQ: one (batch·head, q-block, kv-block) program; dQ in scratch."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    diag_ok = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(diag_ok)
    def _accumulate():
        _, ds, _ = _block_dscores(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref,
            qi, ki, scale, block_q, block_k, causal,
        )
        kb = k_ref[0].astype(jnp.float32)
        acc_ref[:] += lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_kv - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, block_q, block_k,
                causal):
    """dK/dV: one (batch·head, kv-block, q-block) program; both in scratch."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    diag_ok = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(diag_ok)
    def _accumulate():
        p, ds, do = _block_dscores(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref,
            qi, ki, scale, block_q, block_k, causal,
        )
        dv_acc[:] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Bk, D]
        qb = q_ref[0].astype(jnp.float32)
        dk_acc[:] += lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Bk, D]

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _fa_backward(q, k, v, out, lse, g, g_lse, scale, block_q, block_k,
                 causal, interpret):
    """Fused flash backward on [BH, T, D] arrays → (dq, dk, dv).

    ``g_lse`` is the cotangent of the forward's lse output ([BH, T, 1];
    pass zeros when only the attention output is differentiated).
    """
    bh, t, d = q.shape
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # [BH, T, 1]

    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec_dq = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    rowspec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
            causal=causal,
        ),
        out_shape=_sds((bh, t, d), q.dtype, q),
        grid=(bh, t // block_q, t // block_k),
        in_specs=[qspec, kspec_dq, kspec_dq, qspec, rowspec, rowspec,
                  rowspec],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, g, lse, delta, g_lse)

    # kv-major grid: q-row inputs are indexed by the INNER axis here
    qspec_kv = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0))
    kspec_kv = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    rowspec_kv = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
            causal=causal,
        ),
        out_shape=(
            _sds((bh, t, d), k.dtype, q),
            _sds((bh, t, d), v.dtype, q),
        ),
        grid=(bh, t // block_k, t // block_q),
        in_specs=[qspec_kv, kspec_kv, kspec_kv, qspec_kv, rowspec_kv,
                  rowspec_kv, rowspec_kv],
        out_specs=(kspec_kv, kspec_kv),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, g, lse, delta, g_lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fa(q, k, v, scale, block_q, block_k, causal, interpret):
    out, _ = _fa_forward(q, k, v, scale, block_q, block_k, causal, interpret)
    return out


def _fa_fwd(q, k, v, scale, block_q, block_k, causal, interpret):
    out, lse = _fa_forward(q, k, v, scale, block_q, block_k, causal,
                           interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd(scale, block_q, block_k, causal, interpret, res, g):
    q, k, v, out, lse = res
    return _fa_backward(q, k, v, out, lse, g, jnp.zeros_like(lse), scale,
                        block_q, block_k, causal, interpret)


_fa.defvjp(_fa_fwd, _fa_bwd)


def _prep(t, d, scale, interpret, block_q, block_k):
    """Shared wrapper defaults: score scale, interpret-mode autodetect, and
    sublane-aligned block clamps (Mosaic tiling: never clamp to a ragged t)."""
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    align = 32
    block_q = min(block_q, -(-t // align) * align)
    block_k = min(block_k, -(-t // align) * align)
    return scale, interpret, block_q, block_k


def _fold(x, b, t, h, d):
    """[B,T,H,D] -> [B*H, T, D]."""
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _reference_lse(q, k, v, causal, scale):
    """(out, lse) on [BH, T, D] via plain einsums — bwd recompute path for
    the lse-exposing variant."""
    s = jnp.einsum(
        "bqd,bkd->bqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        s = jnp.where(mask[None], s, _NEG)
    lse = jax.scipy.special.logsumexp(s, axis=-1)[..., None]
    p = jnp.exp(s - lse)
    out = jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v).astype(q.dtype)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fa_lse(q, k, v, scale, block_q, block_k, causal, interpret):
    return _fa_forward(q, k, v, scale, block_q, block_k, causal, interpret)


def _fa_lse_fwd(q, k, v, scale, block_q, block_k, causal, interpret):
    out, lse = _fa_forward(q, k, v, scale, block_q, block_k, causal,
                           interpret)
    return (out, lse), (q, k, v, out, lse)


def _fa_lse_bwd(scale, block_q, block_k, causal, interpret, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    return _fa_backward(q, k, v, out, lse, g_out,
                        g_lse.astype(jnp.float32), scale, block_q, block_k,
                        causal, interpret)


_fa_lse.defvjp(_fa_lse_fwd, _fa_lse_bwd)


def flash_attention_with_lse(q, k, v, causal=True, scale=None, block_q=128,
                             block_k=128, interpret=None):
    """Flash attention returning ``(out, lse)`` on the [B, T, H, D] layout.

    ``lse`` is [B, H, T, 1] f32 — the per-row log-sum-exp that lets partial
    attention results over disjoint KV shards merge exactly:
    ``o = Σ_s o_s · exp(lse_s − logaddexp_s lse_s)``.  This is the building
    block ring attention uses to run each ring step through the kernel.
    T must tile by the (aligned) block sizes — ring shards are powers of
    two, so no padding path is carried here.
    """
    b, t, h, d = q.shape
    scale, interpret, block_q, block_k = _prep(
        t, d, scale, interpret, block_q, block_k
    )
    qf = _fold(q, b, t, h, d)
    kf = _fold(k, b, t, h, d)
    vf = _fold(v, b, t, h, d)
    if t % block_q or t % block_k:
        # sub-block / ragged shard: the einsum reference is exact and cheap
        # at small sizes, but it is O(T²) — refuse silently degrading a
        # long-context shard (pad the global sequence upstream instead)
        if t > 1024:
            raise ValueError(
                f"shard length {t} does not tile by blocks "
                f"({block_q},{block_k}) and is too long for the dense "
                "fallback; pad the sequence so shards tile"
            )
        out, lse = _reference_lse(qf, kf, vf, causal, scale)
    else:
        out, lse = _fa_lse(
            qf, kf, vf, scale, block_q, block_k, causal, interpret
        )
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out, lse.reshape(b, h, t, 1)


def flash_attention(q, k, v, causal=True, scale=None, block_q=128,
                    block_k=128, interpret=None):
    """Flash attention with the ``plain_attention`` interface.

    Args:
      q, k, v: [B, T, H, D] (same head count — repeat GQA KV first, as the
        transformer's attention block already does).
      causal: apply the causal mask (q and kv must be the same length).
      scale: score scale; defaults to D**-0.5.
      block_q, block_k: kernel tile sizes (clamped to the padded length).
      interpret: force Pallas interpret mode; default: on for any backend
        without a real TPU.

    Returns [B, T, H, D] in q's dtype.
    """
    b, t, h, d = q.shape
    scale, interpret, block_q, block_k = _prep(
        t, d, scale, interpret, block_q, block_k
    )
    # padded length must tile by BOTH block sizes
    pad = (-t) % math.lcm(block_q, block_k)

    if pad and not causal:
        # non-causal has no positional mask to neutralize padded keys; the
        # ragged remainder is small — use the plain formulation directly
        from client_tpu.parallel.ring_attention import plain_attention

        return plain_attention(q, k, v, causal=False, scale=scale)

    qf = _fold(q, b, t, h, d)
    kf = _fold(k, b, t, h, d)
    vf = _fold(v, b, t, h, d)
    if pad:
        # padded KV rows sit in the causal future of every real Q row (the
        # position mask zeroes them); padded Q rows are sliced off below
        qf = jnp.pad(qf, ((0, 0), (0, pad), (0, 0)))
        kf = jnp.pad(kf, ((0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad), (0, 0)))

    out = _fa(qf, kf, vf, scale, block_q, block_k, causal, interpret)
    if pad:
        out = out[:, :t]
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
