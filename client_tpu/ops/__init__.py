"""TPU kernel library (Pallas) for the framework's hot ops.

The serving/training compute path is XLA-compiled JAX; this package holds
the hand-written Pallas TPU kernels for the operations where blockwise
control over VMEM residency beats what the compiler fuses on its own —
starting with causal flash attention (:mod:`client_tpu.ops.flash_attention`),
the transformer family's dominant op.
"""

from client_tpu.ops.flash_attention import flash_attention  # noqa: F401
