"""Datatype mapping and tensor (de)serialization for the KServe-v2 protocol.

Capability parity with the reference ``tritonclient.utils`` package
(/root/reference/src/python/library/tritonclient/utils/__init__.py:128-345), with
two TPU-native upgrades:

- ``BF16`` is a first-class numpy dtype here via ``ml_dtypes.bfloat16`` (the dtype
  jax itself uses), instead of the reference's uint16-word pack/unpack helpers.
  The word-level helpers are still provided for interop.
- ``to_numpy``/``from_numpy`` bridges accept ``jax.Array`` so device-resident
  tensors flow through the clients without intermediate copies where possible.
"""

import struct

import numpy as np

try:  # ml_dtypes ships with jax; keep the package importable without it.
    import ml_dtypes

    _BF16_DTYPE = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    ml_dtypes = None
    _BF16_DTYPE = None

__all__ = [
    "InferenceServerException",
    "raise_error",
    "np_to_triton_dtype",
    "triton_to_np_dtype",
    "escape_label",
    "serialize_byte_tensor",
    "deserialize_bytes_tensor",
    "serialize_bf16_tensor",
    "deserialize_bf16_tensor",
    "serialized_byte_size",
    "SERVER_READY",
    "SERVER_NOT_READY",
    "SERVER_UNREACHABLE",
    "TENANT_HEADER",
    "stamp_tenant",
]

# The wire key tenant identity rides on (HTTP header name / gRPC metadata
# key — gRPC metadata keys are lowercase by spec).  Lives here, not in
# serve/frontdoor, because BOTH sides speak it: the serving front door
# reads it and the clients' ``tenant=`` constructor kwarg stamps it.
TENANT_HEADER = "x-tenant-id"


def stamp_tenant(headers, tenant):
    """Merge a client's tenant identity into *headers* for one request
    (an explicitly passed x-tenant-id, any case, wins).  Shared by all
    four clients' ``tenant=`` constructor kwarg."""
    if tenant is None:
        return headers
    if headers and any(k.lower() == TENANT_HEADER for k in headers):
        return headers
    merged = dict(headers or {})
    merged[TENANT_HEADER] = tenant
    return merged

# Server health states reported by the clients' ``server_state()`` verb.
# ``is_server_ready()`` keeps its boolean contract; these distinguish the
# two reasons it can answer False — a *draining* server that answered
# not-ready (finish in-flight work, expect recovery or planned removal)
# versus a *dead* one that never answered (route away, open the circuit).
# The distinction is what lets a replica set treat drain and death
# differently (client_tpu.balance).
SERVER_READY = "READY"
SERVER_NOT_READY = "NOT_READY"
SERVER_UNREACHABLE = "UNREACHABLE"


class InferenceServerException(Exception):
    """Error raised for any server-reported or client-side protocol failure.

    Parity: tritonclient.utils.InferenceServerException
    (reference utils/__init__.py:66-126).
    """

    def __init__(self, msg, status=None, debug_details=None):
        self._msg = msg
        self._status = status
        self._debug_details = debug_details
        super().__init__(msg)

    def __str__(self):
        msg = self._msg if self._msg is not None else "Unknown error"
        if self._status is not None:
            msg = f"[{self._status}] {msg}"
        return msg

    def message(self):
        """The error message string."""
        return self._msg

    def status(self):
        """Protocol-specific status code (HTTP status / gRPC StatusCode name)."""
        return self._status

    def debug_details(self):
        """Any low-level exception or payload that accompanied the failure."""
        return self._debug_details


def raise_error(msg):
    """Raise an InferenceServerException with *msg* and no status."""
    raise InferenceServerException(msg=msg)


def escape_label(value):
    """Escape a Prometheus label value (backslash, quote, newline).

    Lives here (a leaf module both halves already import) so the server's
    /metrics renderer and the client-side perf scraper share one escaper
    without perf pulling in the serving stack."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


# KServe-v2 datatype string <-> numpy dtype tables. The wire names are the
# protocol spec (reference utils/__init__.py:128-187 is the same table).
_NP_TO_TRITON = {
    np.dtype(np.bool_): "BOOL",
    np.dtype(np.int8): "INT8",
    np.dtype(np.int16): "INT16",
    np.dtype(np.int32): "INT32",
    np.dtype(np.int64): "INT64",
    np.dtype(np.uint8): "UINT8",
    np.dtype(np.uint16): "UINT16",
    np.dtype(np.uint32): "UINT32",
    np.dtype(np.uint64): "UINT64",
    np.dtype(np.float16): "FP16",
    np.dtype(np.float32): "FP32",
    np.dtype(np.float64): "FP64",
}
if _BF16_DTYPE is not None:
    _NP_TO_TRITON[_BF16_DTYPE] = "BF16"

_TRITON_TO_NP = {v: k for k, v in _NP_TO_TRITON.items()}
_TRITON_TO_NP["BYTES"] = np.dtype(np.object_)

# sizeof on the wire for fixed-width types (BYTES is length-prefixed, see below)
_TRITON_ELEMENT_SIZE = {
    "BOOL": 1,
    "INT8": 1,
    "INT16": 2,
    "INT32": 4,
    "INT64": 8,
    "UINT8": 1,
    "UINT16": 2,
    "UINT32": 4,
    "UINT64": 8,
    "FP16": 2,
    "BF16": 2,
    "FP32": 4,
    "FP64": 8,
}


def np_to_triton_dtype(np_dtype):
    """Map a numpy (or ml_dtypes / jax) dtype to its KServe datatype string."""
    dt = np.dtype(np_dtype)
    if dt in _NP_TO_TRITON:
        return _NP_TO_TRITON[dt]
    if dt.kind in ("O", "S", "U"):
        return "BYTES"
    return None


def triton_to_np_dtype(dtype):
    """Map a KServe datatype string to a numpy dtype (BF16 -> ml_dtypes.bfloat16)."""
    if dtype == "BF16":
        if _BF16_DTYPE is None:
            raise_error("BF16 requires the ml_dtypes package")
        return _BF16_DTYPE
    return _TRITON_TO_NP.get(dtype, None)


def triton_dtype_element_size(dtype):
    """Bytes per element on the wire for fixed-width datatypes; None for BYTES."""
    return _TRITON_ELEMENT_SIZE.get(dtype, None)


def _iter_elements_as_bytes(input_tensor):
    flat = np.ascontiguousarray(input_tensor).flatten()
    for obj in flat:
        if isinstance(obj, (bytes, bytearray)):  # covers np.bytes_ (a bytes subclass)
            yield bytes(obj)
        else:
            yield str(obj).encode("utf-8")


def serialize_byte_tensor(input_tensor):
    """Serialize a BYTES tensor: each element as <u32le length><payload>, row-major.

    Accepts object/bytes/unicode numpy arrays. Returns a 1-D uint8 numpy array
    whose buffer is the wire payload (parity: reference utils/__init__.py:188-240).
    """
    if input_tensor.size == 0:
        return np.empty([0], dtype=np.object_)
    if input_tensor.dtype != np.object_ and input_tensor.dtype.type not in (
        np.bytes_,
        np.str_,
    ):
        raise_error("cannot serialize bytes tensor: invalid datatype")
    parts = []
    for b in _iter_elements_as_bytes(input_tensor):
        parts.append(struct.pack("<I", len(b)))
        parts.append(b)
    blob = b"".join(parts)
    return np.frombuffer(blob, dtype=np.uint8)


def deserialize_bytes_tensor(encoded_tensor, max_elements=None):
    """Inverse of serialize_byte_tensor: wire payload -> 1-D np.object_ array
    of bytes.  ``max_elements`` stops after that many elements — for reading
    out of an shm region whose tail beyond the tensor is arbitrary bytes."""
    strs = []
    offset = 0
    view = memoryview(encoded_tensor)
    n = len(view)
    while offset < n and (max_elements is None or len(strs) < max_elements):
        if offset + 4 > n:
            raise_error("malformed BYTES tensor: truncated length prefix")
        (length,) = struct.unpack_from("<I", view, offset)
        offset += 4
        if offset + length > n:
            raise_error("malformed BYTES tensor: element overruns payload")
        strs.append(bytes(view[offset : offset + length]))
        offset += length
    return np.array(strs, dtype=np.object_)


def serialize_bf16_tensor(input_tensor):
    """Serialize a tensor to BF16 wire bytes.

    Accepts float16/float32/float64 or ml_dtypes.bfloat16 arrays; rounds to
    bfloat16 and returns a uint8 view of the 2-byte little-endian words
    (interop parity with reference utils/__init__.py:276-310).
    """
    if _BF16_DTYPE is None:
        raise_error("BF16 requires the ml_dtypes package")
    arr = np.asarray(input_tensor)
    if arr.dtype != _BF16_DTYPE:
        if arr.dtype.kind != "f":
            raise_error("cannot serialize bf16 tensor: invalid datatype")
        arr = arr.astype(_BF16_DTYPE)
    return np.frombuffer(np.ascontiguousarray(arr).tobytes(), dtype=np.uint8)


def deserialize_bf16_tensor(encoded_tensor):
    """Inverse of serialize_bf16_tensor -> 1-D ml_dtypes.bfloat16 array."""
    if _BF16_DTYPE is None:
        raise_error("BF16 requires the ml_dtypes package")
    return np.frombuffer(bytes(encoded_tensor), dtype=_BF16_DTYPE)


def serialized_byte_size(np_array):
    """Wire size in bytes of *np_array* (length-prefixed accounting for BYTES)."""
    if np_array.dtype == np.object_ or np_array.dtype.type in (np.bytes_, np.str_):
        total = 0
        for b in _iter_elements_as_bytes(np_array):
            total += 4 + len(b)
        return total
    return np_array.nbytes


def to_wire_bytes(array, datatype):
    """Array -> contiguous little-endian wire bytes for *datatype*.

    Device arrays (jax.Array) are converted via ``np.asarray`` which uses dlpack /
    zero-copy paths where the backend allows it.
    """
    arr = np.asarray(array)
    if datatype == "BYTES":
        return serialize_byte_tensor(arr).tobytes()
    if datatype == "BF16":
        return serialize_bf16_tensor(arr).tobytes()
    expected = triton_to_np_dtype(datatype)
    if expected is None:
        raise_error(f"unsupported datatype {datatype}")
    if arr.dtype != expected:
        raise_error(
            f"input array dtype {arr.dtype} does not match datatype {datatype}"
        )
    return np.ascontiguousarray(arr).tobytes()


def from_wire_bytes(buf, datatype, shape):
    """Wire bytes -> numpy array of *datatype* reshaped to *shape*.

    Fixed-width datatypes decode as a zero-copy ``np.frombuffer`` view over
    *buf* (bytes, memoryview, or any C-contiguous buffer) — the hot serving
    path hands transport-owned buffers straight to the model with no copy.
    The view is read-only; consumers that mutate must copy first.
    """
    if datatype == "BYTES":
        arr = deserialize_bytes_tensor(
            buf if isinstance(buf, bytes) else bytes(buf)
        )
    else:
        np_dtype = triton_to_np_dtype(datatype)
        if np_dtype is None:
            raise_error(f"unsupported datatype {datatype}")
        arr = np.frombuffer(buf, dtype=np_dtype)
    return arr.reshape(shape)
