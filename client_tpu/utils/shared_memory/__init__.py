"""System shared-memory utilities (ctypes over the native libcshm_tpu.so).

API parity with the reference's ``tritonclient.utils.shared_memory``
(reference src/python/library/tritonclient/utils/shared_memory/__init__.py:
46-124): create/set/get/destroy POSIX shm regions plus a process-local
registry of mapped regions.  The native library (src/cpp/shm/cshm.cc) does
shm_open + mmap and bulk copies; build it with ``make native``.
"""

import ctypes
import os

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    serialize_byte_tensor,
    triton_to_np_dtype,
)

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libcshm_tpu.so")
_lib = None


def _load():
    global _lib
    if _lib is None:
        if not os.path.exists(_LIB_PATH):
            raise InferenceServerException(
                f"native shared-memory library not built: {_LIB_PATH} "
                "(run `make native`)"
            )
        _lib = ctypes.CDLL(_LIB_PATH)
        _lib.TpuShmCreate.restype = ctypes.c_void_p
        _lib.TpuShmCreate.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        _lib.TpuShmOpen.restype = ctypes.c_void_p
        _lib.TpuShmOpen.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
        _lib.TpuShmWrite.restype = ctypes.c_int
        _lib.TpuShmWrite.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ]
        _lib.TpuShmRead.restype = ctypes.c_int
        _lib.TpuShmRead.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ]
        _lib.TpuShmBaseAddr.restype = ctypes.c_void_p
        _lib.TpuShmBaseAddr.argtypes = [ctypes.c_void_p]
        _lib.TpuShmClose.restype = ctypes.c_int
        _lib.TpuShmClose.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _lib.TpuShmLastError.restype = ctypes.c_char_p
    return _lib


def _last_error(lib):
    msg = lib.TpuShmLastError()
    return msg.decode("utf-8", errors="replace") if msg else "unknown error"


class SharedMemoryRegion:
    """Handle for one created-or-attached system shm region."""

    def __init__(self, triton_shm_name, shm_key, byte_size, native_handle):
        self._triton_shm_name = triton_shm_name
        self._shm_key = shm_key
        self._byte_size = byte_size
        self._handle = native_handle


# name -> SharedMemoryRegion, mirroring the reference's mapped_shm_regions
_mapped_regions = {}


def create_shared_memory_region(triton_shm_name, shm_key, byte_size, create=True):
    """Create (or attach to, with create=False) a POSIX shm region."""
    lib = _load()
    if create:
        handle = lib.TpuShmCreate(shm_key.encode(), byte_size)
    else:
        handle = lib.TpuShmOpen(shm_key.encode(), byte_size, 0)
    if not handle:
        raise InferenceServerException(
            f"unable to create shared memory region '{shm_key}': "
            f"{_last_error(lib)}"
        )
    region = SharedMemoryRegion(triton_shm_name, shm_key, byte_size, handle)
    _mapped_regions[triton_shm_name] = region
    return region


def set_shared_memory_region(shm_handle, input_values, offset=0):
    """Copy a list of numpy arrays into the region back-to-back at offset."""
    lib = _load()
    if not isinstance(input_values, (list, tuple)):
        raise InferenceServerException("input_values must be a list of numpy arrays")
    cur = offset
    for arr in input_values:
        arr = np.asarray(arr)
        if arr.dtype == np.object_ or arr.dtype.type == np.str_:
            raw = serialize_byte_tensor(arr).tobytes()
        else:
            raw = np.ascontiguousarray(arr).tobytes()
        ok = lib.TpuShmWrite(shm_handle._handle, cur, raw, len(raw))
        if ok != 0:
            raise InferenceServerException(
                f"unable to set shared memory region "
                f"'{shm_handle._triton_shm_name}': {_last_error(lib)}"
            )
        cur += len(raw)


def get_contents_as_numpy(shm_handle, datatype, shape, offset=0):
    """Read a tensor of (datatype, shape) out of the region.

    ``datatype`` is a numpy dtype or a KServe datatype string.
    """
    lib = _load()
    if isinstance(datatype, str):
        np_dtype = triton_to_np_dtype(datatype)
        is_bytes = datatype == "BYTES"
    else:
        np_dtype = np.dtype(datatype)
        is_bytes = np_dtype == np.object_
    if is_bytes:
        # read the remainder of the region and deserialize length-prefixed
        size = shm_handle._byte_size - offset
        buf = ctypes.create_string_buffer(size)
        if lib.TpuShmRead(shm_handle._handle, offset, buf, size) != 0:
            raise InferenceServerException(_last_error(lib))
        from client_tpu.utils import deserialize_bytes_tensor

        n = int(np.prod(shape)) if len(shape) else 1
        # stop at exactly n elements: the region's tail past the tensor is
        # arbitrary bytes, not length-prefixed data
        flat = deserialize_bytes_tensor(
            np.frombuffer(buf.raw, np.uint8), max_elements=n
        )
        if flat.size < n:
            raise InferenceServerException(
                f"region holds {flat.size} BYTES elements, need {n}"
            )
        return flat.reshape(shape)
    count = int(np.prod(shape)) if len(shape) else 1
    size = count * np.dtype(np_dtype).itemsize
    buf = ctypes.create_string_buffer(size)
    if lib.TpuShmRead(shm_handle._handle, offset, buf, size) != 0:
        raise InferenceServerException(_last_error(lib))
    return np.frombuffer(buf.raw, dtype=np_dtype).reshape(shape).copy()


def mapped_shared_memory_regions():
    """Names of regions currently mapped by this process."""
    return list(_mapped_regions)


def destroy_shared_memory_region(shm_handle, unlink=True):
    """Unmap the region and (by default) unlink its shm key."""
    lib = _load()
    _mapped_regions.pop(shm_handle._triton_shm_name, None)
    if lib.TpuShmClose(shm_handle._handle, 0 if unlink else 1) != 0:
        raise InferenceServerException(_last_error(lib))
