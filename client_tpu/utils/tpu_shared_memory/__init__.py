"""TPU shared-memory transport: HBM-resident tensor regions.

This is the framework's replacement for the reference's CUDA IPC shared
memory (reference src/c++/library/ipc.h:28-33 and
tritonclient/utils/cuda_shared_memory/ — cudaMalloc + cudaIpcGetMemHandle +
native libccudashm.so): a *device-buffer registry* over JAX/PJRT instead of
cudart, backed by the native ``libctpushm.so`` (src/cpp/shm/ctpushm.cc).

Design (SURVEY.md §2.2/§5.8).  A region has two coupled faces:

- **HBM face** — ``jax.Array`` slots keyed by byte offset.  When client and
  server share a process (in-process server, the triton_c_api analog) the
  server resolves the region through a process-local broker and reads/writes
  the device arrays directly: true zero-copy, no H2D/D2H per request, and
  inference dispatch stays asynchronous.
- **Host window (native)** — a POSIX-shm-backed byte-addressable buffer
  managed by ``libctpushm.so``.  Every region has one; it is the region's
  process-portable face (PJRT has no cudaIpc-style cross-process HBM
  export).  Reads and writes work at *any* byte offset.  Device-side writes
  mark their range dirty and are synced to the window lazily, on first byte
  read — so the async zero-copy path never pays a hidden D2H.

The raw handle (the ``cudaIpcMemHandle_t`` analog, JSON emitted by the
native library): ``{"uuid", "pid", "device_id", "byte_size", "staging_key"}``
where ``staging_key`` is the window's POSIX shm key.

Reads with ``get_contents_as_numpy`` force a D2H sync of dirty ranges;
``get_contents_as_jax`` returns the live device array without synchronizing.
"""

import ctypes
import json
import os
import threading

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    serialize_byte_tensor,
    triton_to_np_dtype,
)

# Process-local broker: uuid -> TpuRegion.  The in-process server resolves
# raw handles here (the PJRT same-process fast path).
_broker = {}
_broker_lock = threading.Lock()

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libctpushm.so")
_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is None:
            if not os.path.exists(_LIB_PATH):
                raise InferenceServerException(
                    f"native TPU shared-memory library not built: {_LIB_PATH} "
                    "(run `make native`)"
                )
            lib = ctypes.CDLL(_LIB_PATH)
            lib.TpuHbmRegionCreate.restype = ctypes.c_void_p
            lib.TpuHbmRegionCreate.argtypes = [ctypes.c_uint64, ctypes.c_int]
            lib.TpuHbmRegionOpen.restype = ctypes.c_void_p
            lib.TpuHbmRegionOpen.argtypes = [ctypes.c_char_p]
            lib.TpuHbmWrite.restype = ctypes.c_int
            lib.TpuHbmWrite.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
            ]
            lib.TpuHbmRead.restype = ctypes.c_int
            lib.TpuHbmRead.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
            ]
            lib.TpuHbmGetRawHandle.restype = ctypes.c_int
            lib.TpuHbmGetRawHandle.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ]
            lib.TpuHbmRegionDestroy.restype = ctypes.c_int
            lib.TpuHbmRegionDestroy.argtypes = [ctypes.c_void_p]
            lib.TpuHbmLastError.restype = ctypes.c_char_p
            _lib = lib
    return _lib


def _last_error(lib):
    msg = lib.TpuHbmLastError()
    return msg.decode("utf-8", errors="replace") if msg else "unknown error"


def _jax():
    import jax  # deferred so pure-protocol users never pay jax import cost

    return jax


class _Window:
    """ctypes wrapper over one native host-window handle.

    The library handle is resolved once at construction; per-operation calls
    never touch the global loader lock.  Negative offsets/sizes are rejected
    here before they can wrap through the unsigned native ABI.
    """

    def __init__(self, lib, handle, byte_size):
        self._lib = lib
        self._handle = handle
        self.byte_size = byte_size

    @classmethod
    def create(cls, byte_size, device_id):
        lib = _load()
        handle = lib.TpuHbmRegionCreate(byte_size, device_id)
        if not handle:
            raise InferenceServerException(
                f"TpuHbmRegionCreate failed: {_last_error(lib)}"
            )
        return cls(lib, handle, byte_size)

    @classmethod
    def open(cls, raw_handle, byte_size):
        lib = _load()
        if isinstance(raw_handle, str):
            raw_handle = raw_handle.encode("utf-8")
        handle = lib.TpuHbmRegionOpen(raw_handle)
        if not handle:
            raise InferenceServerException(
                f"TpuHbmRegionOpen failed: {_last_error(lib)}"
            )
        return cls(lib, handle, byte_size)

    def _live(self):
        if self._handle is None:
            raise InferenceServerException("TPU region window is closed")
        return self._handle

    def write(self, offset, data):
        if offset < 0:
            raise InferenceServerException(f"negative offset {offset}")
        # bytearray must be converted too: ctypes c_void_p rejects it
        buf = data if isinstance(data, bytes) else bytes(data)
        rc = self._lib.TpuHbmWrite(self._live(), offset, buf, len(buf))
        if rc != 0:
            raise InferenceServerException(
                f"TpuHbmWrite failed ({rc}): {_last_error(self._lib)}"
            )

    def read(self, offset, nbytes):
        if offset < 0 or nbytes < 0:
            raise InferenceServerException(
                f"negative offset/size ({offset}, {nbytes})"
            )
        out = ctypes.create_string_buffer(nbytes) if nbytes else b""
        if nbytes == 0:
            return b""
        rc = self._lib.TpuHbmRead(self._live(), offset, out, nbytes)
        if rc != 0:
            raise InferenceServerException(
                f"TpuHbmRead failed ({rc}): {_last_error(self._lib)}"
            )
        return out.raw

    def raw_handle(self):
        buf = ctypes.create_string_buffer(512)
        n = self._lib.TpuHbmGetRawHandle(self._live(), buf, 512)
        if n < 0:
            raise InferenceServerException(
                f"TpuHbmGetRawHandle failed ({n}): {_last_error(self._lib)}"
            )
        return buf.raw[:n]

    def destroy(self):
        if self._handle is not None:
            self._lib.TpuHbmRegionDestroy(self._handle)
            self._handle = None


class TpuRegion:
    """One named HBM region: device-array slots + native byte window."""

    def __init__(self, name, byte_size, device_id):
        self.name = name
        self.byte_size = byte_size
        self.device_id = device_id
        self._window = _Window.create(byte_size, device_id)
        desc = json.loads(self._window.raw_handle())
        self.uuid = desc["uuid"]
        self.staging_key = desc["staging_key"]
        self._slots = {}  # offset -> jax.Array | np.ndarray (BYTES only)
        self._dirty = set()  # offsets whose window bytes are stale
        self._lock = threading.Lock()

    # -- slot access --------------------------------------------------------

    def _device(self):
        jax = _jax()
        devs = jax.devices()
        if self.device_id >= len(devs):
            raise InferenceServerException(
                f"TPU device {self.device_id} not present ({len(devs)} devices)"
            )
        return devs[self.device_id]

    def write_array(self, offset, arr):
        """Place a tensor at ``offset``; device_put unless already on device.

        Host tensors mirror their bytes into the window immediately (cheap
        memcpy); device tensors only mark the range dirty — the D2H happens
        lazily on the first byte-level read, never on the dispatch path.
        """
        jax = _jax()
        host_bytes = None
        if isinstance(arr, np.ndarray) and arr.dtype == np.object_:
            raw = serialize_byte_tensor(arr)
            host_bytes = raw.tobytes()
            nbytes = len(host_bytes)
            stored = arr  # BYTES stay host-side; devices hold no string type
        elif isinstance(arr, jax.Array):
            nbytes = arr.dtype.itemsize * int(np.prod(arr.shape))
            stored = arr
        else:
            arr = np.ascontiguousarray(arr)
            host_bytes = arr.tobytes()
            nbytes = len(host_bytes)
            stored = jax.device_put(arr, self._device())
        if offset < 0 or offset + nbytes > self.byte_size:
            raise InferenceServerException(
                f"write of {nbytes} bytes at offset {offset} overruns TPU "
                f"region '{self.name}' ({self.byte_size} bytes)"
            )
        with self._lock:
            # drop slots this write fully or partially overlaps; a dirty slot
            # only PARTIALLY covered is flushed to the window first so its
            # non-overlapped bytes survive (the byte-addressable contract).
            # A fully-covered slot is simply replaced — flushing it would put
            # a hidden D2H on the hot full-overwrite path (every per-request
            # output write lands at the same offset/size).
            for off, old in list(self._slots.items()):
                old_n = _slot_nbytes(old)
                if off < offset + nbytes and offset < off + old_n:
                    if off in self._dirty and not (
                        offset <= off and off + old_n <= offset + nbytes
                    ):
                        self._flush_slot_locked(off, old)
                    del self._slots[off]
                    self._dirty.discard(off)
            self._slots[offset] = stored
            if host_bytes is not None:
                self._window.write(offset, host_bytes)
            else:
                self._dirty.add(offset)
        return nbytes

    def read(self, offset, nbytes):
        """Byte-addressable read at any offset (syncs dirty device slots).

        The D2H transfer of dirty slots happens OUTSIDE the region lock:
        concurrent readers (e.g. perf-harness completion-sync workers all
        polling the same output region) each pay their own link RTT in
        parallel instead of serializing behind one lock-held transfer — on a
        tunneled device that is the difference between N×RTT and ~1×RTT for
        N concurrent syncs."""
        if offset < 0 or nbytes < 0 or offset + nbytes > self.byte_size:
            raise InferenceServerException(
                f"read of {nbytes} bytes at offset {offset} overruns TPU "
                f"region '{self.name}' ({self.byte_size} bytes)"
            )
        with self._lock:
            base = self._window.read(offset, nbytes)
            snaps = [
                (off, self._slots[off])
                for off in sorted(self._dirty)
                if off in self._slots
                and off < offset + nbytes
                and offset < off + _slot_nbytes(self._slots[off])
            ]
            for off in list(self._dirty):
                if off not in self._slots:
                    self._dirty.discard(off)
        if not snaps:
            return base
        # D2H outside the lock — concurrent readers transfer in parallel —
        # then overlay the snapshot bytes over the window view locally.  The
        # reader observes the region as of read start even if writers keep
        # re-dirtying the same offsets (the old settle-under-the-lock loop
        # could chase a continuously-rewritten slot for seconds while
        # serializing every other reader behind it).
        flushed = [
            (off, slot, np.ascontiguousarray(np.asarray(slot)).tobytes())
            for off, slot in snaps
        ]
        buf = bytearray(base)
        for off, slot, host in flushed:
            lo = max(off, offset)
            hi = min(off + len(host), offset + nbytes)
            if lo < hi:
                buf[lo - offset : hi - offset] = host[lo - off : hi - off]
        with self._lock:
            # opportunistic write-back: only what no concurrent write replaced
            for off, slot, host in flushed:
                if self._slots.get(off) is slot and off in self._dirty:
                    self._window.write(off, host)
                    self._dirty.discard(off)
        return bytes(buf)

    def write(self, offset, data):
        """Byte-addressable write (drops any device slots it overlaps)."""
        if offset < 0 or offset + len(data) > self.byte_size:
            raise InferenceServerException(
                f"write of {len(data)} bytes at offset {offset} overruns TPU "
                f"region '{self.name}' ({self.byte_size} bytes)"
            )
        with self._lock:
            for off, old in list(self._slots.items()):
                old_n = _slot_nbytes(old)
                if off < offset + len(data) and offset < off + old_n:
                    # flush only partially-covered dirty slots (see write_array)
                    if off in self._dirty and not (
                        offset <= off and off + old_n <= offset + len(data)
                    ):
                        self._flush_slot_locked(off, old)
                    del self._slots[off]
                    self._dirty.discard(off)
            self._window.write(offset, data)

    def _flush_slot_locked(self, off, slot):
        """D2H-sync one device slot's bytes into the window (lock held)."""
        host = np.asarray(slot)
        self._window.write(off, np.ascontiguousarray(host).tobytes())

    def _sync_dirty(self, offset, nbytes):
        """Flush dirty device slots overlapping [offset, offset+nbytes) into
        the window.  Caller holds self._lock."""
        for off in sorted(self._dirty):
            slot = self._slots.get(off)
            if slot is None:
                self._dirty.discard(off)
                continue
            n = _slot_nbytes(slot)
            if off < offset + nbytes and offset < off + n:
                self._flush_slot_locked(off, slot)
                self._dirty.discard(off)

    def read_array(self, offset, byte_size, datatype=None, shape=None):
        """Zero-copy read when the stored device array at ``offset`` matches;
        byte-window reconstruction for any other offset/dtype/shape."""
        with self._lock:
            a = self._slots.get(offset)
        if datatype is None:
            if a is None:
                raise InferenceServerException(
                    f"no tensor at offset {offset} of TPU region '{self.name}'"
                )
            return a
        if datatype == "BYTES":
            if isinstance(a, np.ndarray) and a.dtype == np.object_:
                return a.reshape(shape) if shape is not None else a
            from client_tpu.utils import deserialize_bytes_tensor

            raw = self.read(offset, byte_size or self.byte_size - offset)
            # cap at shape-many elements: the region's tail past the tensor
            # is arbitrary bytes, not length-prefixed data (a 0-d shape []
            # caps at 1 element, matching the `shape is not None` reshape)
            n = int(np.prod(shape)) if shape is not None else None
            arr = deserialize_bytes_tensor(raw, max_elements=n)
            if n is not None and arr.size < n:
                raise InferenceServerException(
                    f"region holds {arr.size} BYTES elements, need {n}"
                )
            return arr.reshape(shape) if shape is not None else arr
        np_dtype = triton_to_np_dtype(datatype)
        if np_dtype is None:
            raise InferenceServerException(f"unsupported datatype {datatype}")
        want = np.dtype(np_dtype)
        if (
            a is not None
            and hasattr(a, "dtype")
            and a.dtype == want
            and (shape is None or list(a.shape) == list(shape))
        ):
            return a  # zero-copy device array
        # any other offset/dtype/shape: reconstruct from window bytes
        raw = self.read(offset, byte_size)
        out = np.frombuffer(raw, dtype=want)
        return out.reshape(shape) if shape is not None else out

    def destroy(self):
        with self._lock:
            self._slots.clear()
            self._dirty.clear()
            self._window.destroy()

    def raw_handle(self):
        return self._window.raw_handle()


class TpuWindowRegion:
    """Server-side attachment to a foreign process's region: byte window
    only (the HBM face is not exportable across processes — reads
    reconstruct from bytes, writes land in the window)."""

    def __init__(self, descriptor):
        self.descriptor = descriptor
        self.byte_size = descriptor["byte_size"]
        self._window = _Window.open(json.dumps(descriptor), self.byte_size)
        self._lock = threading.Lock()

    def read(self, offset, nbytes):
        if offset < 0 or nbytes < 0 or offset + nbytes > self.byte_size:
            raise InferenceServerException(
                f"read of {nbytes} bytes at offset {offset} overruns TPU "
                "region window"
            )
        with self._lock:
            return self._window.read(offset, nbytes)

    def write(self, offset, data):
        if offset < 0 or offset + len(data) > self.byte_size:
            raise InferenceServerException(
                f"write of {len(data)} bytes at offset {offset} overruns TPU "
                "region window"
            )
        with self._lock:
            self._window.write(offset, data)

    def read_array(self, offset, byte_size, datatype=None, shape=None):
        from client_tpu.utils import from_wire_bytes

        raw = self.read(offset, byte_size)
        return from_wire_bytes(raw, datatype, shape)

    def write_array(self, offset, arr):
        from client_tpu.utils import np_to_triton_dtype, to_wire_bytes

        host = np.asarray(arr)
        raw = to_wire_bytes(host, np_to_triton_dtype(host.dtype))
        self.write(offset, raw)
        return len(raw)

    def close(self):
        # same lock as read/write: a concurrent request can never race the
        # munmap (use-after-unmap); late calls see a closed-window error
        with self._lock:
            self._window.destroy()


def resolve_inprocess(descriptor):
    """Server-side: map a raw-handle descriptor to a live TpuRegion when the
    client shares this process; None otherwise."""
    if descriptor.get("pid") != os.getpid():
        return None
    with _broker_lock:
        return _broker.get(descriptor.get("uuid"))


# -- public API (parity with cuda_shared_memory/__init__.py:46-120) ---------


def create_shared_memory_region(triton_shm_name, byte_size, device_id=0,
                                staging_key=None):
    """Allocate a TPU HBM region (device slots + native host window).

    ``staging_key`` is accepted for backward compatibility and ignored: every
    region now has a native window whose shm key rides the raw handle.
    """
    region = TpuRegion(triton_shm_name, byte_size, device_id)
    with _broker_lock:
        _broker[region.uuid] = region
    return region


def get_raw_handle(shm_handle):
    """Serializable descriptor to pass to register_tpu_shared_memory."""
    return shm_handle.raw_handle()


def set_shared_memory_region(shm_handle, input_values, offset=0):
    """Copy a list of tensors (numpy or jax.Array) into the region
    back-to-back starting at ``offset``."""
    if not isinstance(input_values, (list, tuple)):
        raise InferenceServerException("input_values must be a list of tensors")
    cur = offset
    for arr in input_values:
        cur += shm_handle.write_array(cur, arr)


def get_contents_as_numpy(shm_handle, datatype, shape, offset=0):
    """Materialize the tensor at ``offset`` host-side (forces D2H sync of
    dirty device slots overlapping the range)."""
    if isinstance(datatype, str):
        wire = datatype
    else:
        from client_tpu.utils import np_to_triton_dtype

        wire = np_to_triton_dtype(np.dtype(datatype))
    count = int(np.prod(shape)) if len(shape) else 1
    if wire == "BYTES":
        return shm_handle.read_array(offset, 0, "BYTES", shape)
    itemsize = np.dtype(triton_to_np_dtype(wire)).itemsize
    arr = shm_handle.read_array(offset, count * itemsize, wire, list(shape))
    return np.asarray(arr)


def get_contents_as_jax(shm_handle, offset=0):
    """The live device array at ``offset`` — no synchronization, no copy."""
    return shm_handle.read_array(offset, 0)


def allocated_shared_memory_regions():
    with _broker_lock:
        return [r.name for r in _broker.values()]


def destroy_shared_memory_region(shm_handle):
    with _broker_lock:
        _broker.pop(shm_handle.uuid, None)
    shm_handle.destroy()


def _slot_nbytes(a):
    if isinstance(a, np.ndarray) and a.dtype == np.object_:
        return serialize_byte_tensor(a).nbytes
    return a.dtype.itemsize * int(np.prod(a.shape))
