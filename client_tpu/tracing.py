"""Client-side request tracing: W3C-style trace context + span records.

The client half of the end-to-end tracing subsystem (the server half lives
in ``client_tpu.serve.tracing``).  All four clients accept an opt-in
``tracer=ClientTracer(...)`` constructor argument; a sampled ``infer`` then

- records client-observed timestamps (request start, serialize end, one
  ATTEMPT_START/ATTEMPT_END pair per transport attempt — retries from
  ``client_tpu.resilience`` show up as repeated pairs, request end), and
- propagates a W3C ``traceparent`` (HTTP header / gRPC metadata) so the
  server's span (see serve/tracing.py) joins the client span under one
  trace id.

Trace files are newline-delimited JSON records (one object per line, the
Triton trace-record shape: ids + a ``timestamps`` list of {name, ns}),
append-only so a client and an in-process server can share one file and a
reader can correlate their records by ``trace_id``.
"""

import collections
import contextlib
import json
import os
import re
import threading
import time

__all__ = [
    "ClientTrace",
    "ClientTracer",
    "client_span",
    "attempt_span",
    "format_traceparent",
    "gen_span_id",
    "gen_trace_id",
    "parse_traceparent",
    "append_trace_record",
    "read_trace_file",
]

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def gen_trace_id():
    """128-bit trace id, lowercase hex (W3C trace-context form)."""
    return os.urandom(16).hex()


def gen_span_id():
    """64-bit span id, lowercase hex."""
    return os.urandom(8).hex()


def format_traceparent(trace_id, span_id):
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header):
    """(trace_id, span_id) from a traceparent header, or None if absent
    or malformed (a bad header must never fail the request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    return m.group(1), m.group(2)


def append_trace_record(path, record):
    """Append one JSON trace record (single line) to *path*."""
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(record, separators=(",", ":")) + "\n")


def read_trace_file(path):
    """All trace records from *path* (JSON-lines, or one JSON array)."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("["):
        return json.loads(stripped)
    return [json.loads(line) for line in text.splitlines() if line.strip()]


class ClientTrace:
    """One traced client request: a span id under a trace id plus the
    client-observed timestamp timeline."""

    def __init__(self, trace_id, span_id, model_name=""):
        self.trace_id = trace_id
        self.span_id = span_id
        self.model_name = model_name
        self.timestamps = []
        self.error = None

    def event(self, name, ns=None, endpoint=None):
        record = {"name": name, "ns": time.time_ns() if ns is None else ns}
        if endpoint:
            record["endpoint"] = endpoint
        self.timestamps.append(record)

    def traceparent(self):
        return format_traceparent(self.trace_id, self.span_id)

    def attempts(self):
        """Transport attempts observed (retries show as extra pairs)."""
        return sum(
            1 for t in self.timestamps if t["name"] == "CLIENT_ATTEMPT_START"
        )

    def attempt_endpoints(self):
        """Endpoint of each transport attempt, in order — a replica-set
        failover shows as consecutive attempts on different endpoints."""
        return [
            t.get("endpoint", "")
            for t in self.timestamps
            if t["name"] == "CLIENT_ATTEMPT_START"
        ]

    def to_json(self):
        record = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "source": "client",
            "model_name": self.model_name,
            "timestamps": list(self.timestamps),
        }
        if self.error:
            record["error"] = self.error
        return record


@contextlib.contextmanager
def client_span(tracer, model_name, context_key=None):
    """Bracket one client request: sample a trace from *tracer* (yields
    None when tracing is off or the request is not sampled), record
    CLIENT_REQUEST_START/END, capture the error on failure, and always
    complete the trace.  The shared request-bracket all four clients use —
    span semantics change here, once, not per transport.  Synchronous on
    purpose: the trace calls never block, so coroutine clients use it too.

    ``context_key`` pins every request sharing the key under ONE trace id
    (each request still gets its own span): the replicated clients key it
    on the sequence id, so all steps of a sequence — including the retries
    and failover hops after a replica death — join as one trace.
    """
    trace = (
        tracer.sample(model_name, context_key=context_key)
        if tracer is not None else None
    )
    if trace is None:
        yield None
        return
    trace.event("CLIENT_REQUEST_START")
    try:
        yield trace
        trace.event("CLIENT_REQUEST_END")
    except Exception as e:
        trace.error = str(e)
        raise
    finally:
        tracer.complete(trace)


@contextlib.contextmanager
def attempt_span(trace, endpoint=None):
    """Bracket one transport attempt with CLIENT_ATTEMPT_START/END (a
    no-op when the request is untraced) — retries through the resilience
    layer show as repeated pairs on the same trace.  ``endpoint`` stamps
    the attempt with the replica it targeted, so a replica-set failover
    hop is visible as consecutive attempts on different endpoints."""
    if trace is None:
        yield
        return
    trace.event("CLIENT_ATTEMPT_START", endpoint=endpoint)
    try:
        yield
    finally:
        trace.event("CLIENT_ATTEMPT_END", endpoint=endpoint)


class ClientTracer:
    """Samples and collects client-side traces.

    ``trace_rate=N`` samples the first of every N requests (1 = every
    request).  Completed traces are kept on a bounded deque
    (:attr:`traces`) and, when ``trace_file`` is set, appended to the file
    as JSON-lines — point it at the server's ``trace_file`` to get the
    combined client+server timeline in one place.
    """

    def __init__(self, trace_file="", trace_rate=1, max_traces=1000):
        self.trace_file = trace_file
        self.trace_rate = max(int(trace_rate), 1)
        self._lock = threading.Lock()
        self._seen = 0
        self.traces = collections.deque(maxlen=max_traces)
        # context_key -> pinned decision: a trace id (every request
        # sharing the key joins one trace) or None (the key's FIRST
        # request was unsampled, so the whole sequence stays untraced —
        # a sequence is traced whole or not at all, never from a random
        # mid-step).  Bounded; release_context drops a finished key.
        self._pinned = collections.OrderedDict()

    def sample(self, model_name="", context_key=None):
        """A new ClientTrace for this request, or None (not sampled).

        With ``context_key``, the key's FIRST request decides sampling
        for every request sharing it: sampled mints the shared trace id,
        unsampled pins the whole key untraced — so with ``trace_rate``
        > 1 a sequence is traced from its first step or not at all."""
        with self._lock:
            seen = self._seen
            self._seen += 1
            if context_key is not None and context_key in self._pinned:
                trace_id = self._pinned[context_key]
                if trace_id is None:
                    return None
                return ClientTrace(trace_id, gen_span_id(), model_name)
        sampled = not seen % self.trace_rate
        if context_key is None:
            if not sampled:
                return None
            return ClientTrace(gen_trace_id(), gen_span_id(), model_name)
        with self._lock:
            trace_id = self._pinned.setdefault(
                context_key, gen_trace_id() if sampled else None
            )
            self._pinned.move_to_end(context_key)
            while len(self._pinned) > 4096:
                self._pinned.popitem(last=False)
        if trace_id is None:
            return None
        return ClientTrace(trace_id, gen_span_id(), model_name)

    def release_context(self, context_key):
        """Drop a pinned trace id (the sequence ended; a restarted
        sequence id then starts a fresh trace)."""
        with self._lock:
            self._pinned.pop(context_key, None)

    def complete(self, trace):
        with self._lock:
            self.traces.append(trace)
        if self.trace_file:
            try:
                append_trace_record(self.trace_file, trace.to_json())
            except OSError:
                pass  # tracing must never fail the request path
