"""Load-balancing policies: which healthy replica gets the next request.

One interface — ``pick(candidates, request_ctx)`` over the pool's eligible
:class:`~client_tpu.balance.pool.Endpoint` objects — behind four shapes:

- **round-robin**: strict rotation; the right default when replicas are
  homogeneous and requests are similar-sized.
- **least-inflight**: route to the replica with the fewest outstanding
  requests; adapts to heterogeneous replicas and long-tailed request
  durations (a slow replica accumulates inflight and stops receiving).
- **power-of-two-choices**: sample two random replicas, take the less
  loaded (Mitzenmacher) — least-inflight's adaptivity without the
  herd-to-the-minimum behavior when many clients share stale load views.
- **weighted**: stationary weighted-random split, for canaries and
  capacity-skewed fleets.

Policies are invoked with the pool lock held: they may keep unguarded
internal state (the round-robin cursor), and they must never block or
call back into the pool.
"""

import random

from client_tpu.utils import InferenceServerException

__all__ = [
    "Policy",
    "RoundRobin",
    "LeastInflight",
    "PowerOfTwoChoices",
    "Weighted",
    "make_policy",
]


class Policy:
    """Picks one endpoint from the eligible candidates.

    ``candidates`` is a non-empty list of Endpoint objects (already
    filtered to routable ones); ``request_ctx`` is an optional dict of
    request attributes (``model_name``, ...) for content-aware policies.
    """

    name = "policy"

    def pick(self, candidates, request_ctx=None):
        raise NotImplementedError


class RoundRobin(Policy):
    name = "round-robin"

    def __init__(self):
        self._cursor = 0

    def pick(self, candidates, request_ctx=None):
        # The candidate set shrinks and grows as health changes; a plain
        # modular cursor still spreads load evenly within any stable set.
        self._cursor = (self._cursor + 1) % (1 << 30)
        return candidates[self._cursor % len(candidates)]


class LeastInflight(Policy):
    name = "least-inflight"

    def __init__(self):
        self._cursor = 0

    def pick(self, candidates, request_ctx=None):
        # rotate the tie-break start point so equal-load replicas share
        # work instead of the first one absorbing every burst
        self._cursor = (self._cursor + 1) % (1 << 30)
        n = len(candidates)
        best = None
        for i in range(n):
            candidate = candidates[(self._cursor + i) % n]
            if best is None or candidate.inflight < best.inflight:
                best = candidate
        return best


class PowerOfTwoChoices(Policy):
    name = "power-of-two"

    def __init__(self, rng=None):
        self._rng = rng or random.Random()

    def pick(self, candidates, request_ctx=None):
        if len(candidates) == 1:
            return candidates[0]
        a, b = self._rng.sample(candidates, 2)
        return a if a.inflight <= b.inflight else b


class Weighted(Policy):
    """Weighted-random split over ``Endpoint.weight`` (weight 0 removes an
    endpoint from this policy's rotation without marking it unhealthy —
    the canary-off switch)."""

    name = "weighted"

    def __init__(self, rng=None):
        self._rng = rng or random.Random()

    def pick(self, candidates, request_ctx=None):
        weights = [max(float(e.weight), 0.0) for e in candidates]
        total = sum(weights)
        if total <= 0:  # all zero-weight: fall back to uniform
            return self._rng.choice(candidates)
        x = self._rng.uniform(0.0, total)
        for endpoint, w in zip(candidates, weights):
            x -= w
            if x <= 0:
                return endpoint
        return candidates[-1]


_POLICIES = {
    RoundRobin.name: RoundRobin,
    LeastInflight.name: LeastInflight,
    PowerOfTwoChoices.name: PowerOfTwoChoices,
    Weighted.name: Weighted,
}


def make_policy(spec):
    """Policy instance from a name ('round-robin', 'least-inflight',
    'power-of-two', 'weighted') or an already-built Policy."""
    if isinstance(spec, Policy):
        return spec
    cls = _POLICIES.get(str(spec))
    if cls is None:
        raise InferenceServerException(
            f"unknown balancing policy '{spec}' "
            f"(choose from {sorted(_POLICIES)})"
        )
    return cls()
