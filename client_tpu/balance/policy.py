"""Load-balancing policies: which healthy replica gets the next request.

One interface — ``pick(candidates, request_ctx)`` over the pool's eligible
:class:`~client_tpu.balance.pool.Endpoint` objects — behind four shapes:

- **round-robin**: strict rotation; the right default when replicas are
  homogeneous and requests are similar-sized.
- **least-inflight**: route to the replica with the fewest outstanding
  requests; adapts to heterogeneous replicas and long-tailed request
  durations (a slow replica accumulates inflight and stops receiving).
- **power-of-two-choices**: sample two random replicas, take the less
  loaded (Mitzenmacher) — least-inflight's adaptivity without the
  herd-to-the-minimum behavior when many clients share stale load views.
- **weighted**: stationary weighted-random split, for canaries and
  capacity-skewed fleets.
- **sticky**: sequence-affine routing — every request of one sequence id
  lands on one replica (the server's ``SequenceContext`` state lives on
  exactly one replica); when that replica dies mid-sequence the policy
  remaps the sequence and surfaces :class:`SequenceRestartError` so the
  caller restarts the sequence instead of silently splitting its state
  across replicas.
- **prefix-aware**: cache-affinity routing for the fleet cache tier —
  route to the replica whose gossiped digest summary holds the request's
  LONGEST cached prefix (``request_ctx['prefix_digests']``, the
  cumulative block-chain digests of ``client_tpu.serve.fleet.
  chain_digests``), multiplying the prefix cache's prefill savings by
  the fleet hit rate; ties and digest-less requests fall back to
  least-inflight, so stale gossip degrades to load balancing.

Policies are invoked with the pool lock held: they may keep unguarded
internal state (the round-robin cursor, the sticky sequence map), and
they must never block or call back into the pool.
"""

import collections
import random

from client_tpu.utils import InferenceServerException

__all__ = [
    "Policy",
    "RoundRobin",
    "LeastInflight",
    "PowerOfTwoChoices",
    "Weighted",
    "Sticky",
    "PrefixAware",
    "SequenceRestartError",
    "make_policy",
]


class SequenceRestartError(InferenceServerException):
    """The replica holding this sequence's state is gone; the sequence was
    remapped to a fresh replica.

    Raised by the sticky policy instead of silently routing a mid-sequence
    request at a replica that never saw the sequence (which would fork its
    state).  The condition is *restartable*: the new mapping is already
    installed, so re-sending the sequence from its start
    (``sequence_start=True``) lands it whole on the new replica.  The
    status is 409 (conflict) — deliberately NOT in the retry layer's
    retryable set, because replaying only the failed request (what a retry
    would do) is exactly the state split this error exists to prevent.
    """

    def __init__(self, sequence_id, dead_endpoint, new_endpoint):
        super().__init__(
            msg=(
                f"sequence {sequence_id!r} was pinned to "
                f"{dead_endpoint!r}, which is no longer routable; remapped "
                f"to {new_endpoint!r} — restart the sequence "
                "(sequence_start=True) to rebuild its state there"
            ),
            status="409",
        )
        self.sequence_id = sequence_id
        self.dead_endpoint = dead_endpoint
        self.new_endpoint = new_endpoint


class Policy:
    """Picks one endpoint from the eligible candidates.

    ``candidates`` is a non-empty list of Endpoint objects (already
    filtered to routable ones); ``request_ctx`` is an optional dict of
    request attributes (``model_name``, ...) for content-aware policies.
    """

    name = "policy"

    def pick(self, candidates, request_ctx=None):
        raise NotImplementedError


class RoundRobin(Policy):
    name = "round-robin"

    def __init__(self):
        self._cursor = 0

    def pick(self, candidates, request_ctx=None):
        # The candidate set shrinks and grows as health changes; a plain
        # modular cursor still spreads load evenly within any stable set.
        self._cursor = (self._cursor + 1) % (1 << 30)
        return candidates[self._cursor % len(candidates)]


class LeastInflight(Policy):
    name = "least-inflight"

    def __init__(self):
        self._cursor = 0

    def pick(self, candidates, request_ctx=None):
        # rotate the tie-break start point so equal-load replicas share
        # work instead of the first one absorbing every burst
        self._cursor = (self._cursor + 1) % (1 << 30)
        n = len(candidates)
        best = None
        for i in range(n):
            candidate = candidates[(self._cursor + i) % n]
            if best is None or candidate.inflight < best.inflight:
                best = candidate
        return best


class PowerOfTwoChoices(Policy):
    name = "power-of-two"

    def __init__(self, rng=None):
        self._rng = rng or random.Random()

    def pick(self, candidates, request_ctx=None):
        if len(candidates) == 1:
            return candidates[0]
        a, b = self._rng.sample(candidates, 2)
        return a if a.inflight <= b.inflight else b


class Weighted(Policy):
    """Weighted-random split over ``Endpoint.weight`` (weight 0 removes an
    endpoint from this policy's rotation without marking it unhealthy —
    the canary-off switch)."""

    name = "weighted"

    def __init__(self, rng=None):
        self._rng = rng or random.Random()

    def pick(self, candidates, request_ctx=None):
        # the probation slow-start ramp is applied by the pool's candidate
        # thinning BEFORE any policy runs (one mechanism for every policy);
        # scaling weights here too would compound the penalty to ~f^2
        weights = [max(float(e.weight), 0.0) for e in candidates]
        total = sum(weights)
        if total <= 0:  # all zero-weight: fall back to uniform
            return self._rng.choice(candidates)
        x = self._rng.uniform(0.0, total)
        for endpoint, w in zip(candidates, weights):
            x -= w
            if x <= 0:
                return endpoint
        return candidates[-1]


class Sticky(Policy):
    """Sequence-affine routing over ``request_ctx['sequence_id']``.

    Requests without a sequence id fall through to *fallback* (so one
    pool serves mixed stateless + sequence traffic).  A sequence's first
    request (or any ``sequence_start``) maps it to a fallback-picked
    replica; later requests return the mapped replica as long as it is
    still a candidate.  When it is not — dead, drained, retired, or
    excluded after a failed attempt — the policy remaps the sequence to a
    fresh replica and raises :class:`SequenceRestartError` (see its
    docstring for the restart contract) — UNLESS the request context
    carries ``sequence_durable``: durable sequences replicate their
    server-side state through the fleet tier's sequence lane, the
    survivor rebuilds the context from a peer snapshot on first touch,
    and the remap is silent.  ``sequence_end`` drops the mapping; an LRU
    bound (*max_sequences*) keeps abandoned sequences from pinning the
    map forever.
    """

    name = "sticky"

    def __init__(self, fallback="round-robin", max_sequences=100000):
        self._fallback = make_policy(fallback)
        self._map = collections.OrderedDict()  # sequence_id -> endpoint url
        self._max_sequences = int(max_sequences)

    def sequences(self):
        """{sequence_id: url} snapshot (test/introspection hook)."""
        return dict(self._map)

    def _remember(self, seq_id, url):
        self._map[seq_id] = url
        self._map.move_to_end(seq_id)
        while len(self._map) > self._max_sequences:
            self._map.popitem(last=False)

    def pick(self, candidates, request_ctx=None):
        ctx = request_ctx or {}
        seq_id = ctx.get("sequence_id") or 0
        if not seq_id:
            return self._fallback.pick(candidates, request_ctx)
        url = self._map.get(seq_id)
        if url is not None:
            # honor the mapping whenever the pinned replica is routable —
            # including on sequence_start, so a restart after
            # SequenceRestartError lands on the remap the error installed
            for endpoint in candidates:
                if endpoint.url == url:
                    if ctx.get("sequence_end"):
                        self._map.pop(seq_id, None)
                    else:
                        self._map.move_to_end(seq_id)
                    return endpoint
        replacement = self._fallback.pick(candidates, request_ctx)
        if ctx.get("sequence_end"):  # one-shot / final step: nothing to pin
            self._map.pop(seq_id, None)
        else:
            self._remember(seq_id, replacement.url)
        if url is not None and not ctx.get("sequence_start"):
            if ctx.get("sequence_durable"):
                # durable sequences replicate their state through the
                # fleet tier (SequenceContext snapshots, see serve/fleet
                # "sequence lane"): the survivor rebuilds the sequence
                # from a peer snapshot on first touch, so the remap is
                # SILENT — the client never sees the replica die
                return replacement
            # the pinned replica is gone mid-sequence: the remap is
            # installed, but the caller must rebuild the state there
            raise SequenceRestartError(seq_id, url, replacement.url)
        return replacement


class PrefixAware(Policy):
    """Cache-affinity routing over ``request_ctx['prefix_digests']``.

    The context value is the request's cumulative block-chain digest
    list (``client_tpu.serve.fleet.chain_digests``: ``digests[i]``
    identifies the first ``i + 1`` full token blocks).  Each candidate's
    ``Endpoint.summary`` is the digest set its replica gossiped —
    piggybacked on the pool's health probes
    (``EndpointPool.set_summary``).  The pick is the replica holding the
    request's LONGEST cached prefix: its trie (or fleet store) already
    has those blocks, so routing there turns per-replica prefill savings
    into fleet-level savings without any peer fetch at all.

    Degradation is deliberate: requests without digests, candidates
    without summaries (stale/never-gossiped), and ties all fall through
    to *fallback* (least-inflight by default) — affinity is a hint, load
    balance is the floor, and a wrong/stale summary can only cost the
    peer-fetch the fleet tier would have done anyway.
    """

    name = "prefix-aware"

    def __init__(self, fallback="least-inflight"):
        self._fallback = make_policy(fallback)

    @staticmethod
    def _depth(digests, summary):
        """Longest cached prefix: the deepest cumulative digest the
        summary holds (walked longest-first — chain digests compose, so
        holding digest i without i-1 only happens under store eviction,
        and then the deeper hit is still the better answer)."""
        for i in range(len(digests) - 1, -1, -1):
            if digests[i] in summary:
                return i + 1
        return 0

    def pick(self, candidates, request_ctx=None):
        ctx = request_ctx or {}
        digests = ctx.get("prefix_digests") or ()
        if not digests:
            return self._fallback.pick(candidates, request_ctx)
        best_depth = 0
        best = []
        for endpoint in candidates:
            summary = getattr(endpoint, "summary", None) or ()
            depth = self._depth(digests, summary)
            if depth > best_depth:
                best_depth, best = depth, [endpoint]
            elif depth == best_depth and best_depth > 0:
                best.append(endpoint)
        if not best:
            return self._fallback.pick(candidates, request_ctx)
        if len(best) == 1:
            return best[0]
        return self._fallback.pick(best, request_ctx)


_POLICIES = {
    RoundRobin.name: RoundRobin,
    LeastInflight.name: LeastInflight,
    PowerOfTwoChoices.name: PowerOfTwoChoices,
    Weighted.name: Weighted,
    Sticky.name: Sticky,
    PrefixAware.name: PrefixAware,
}


def make_policy(spec):
    """Policy instance from a name ('round-robin', 'least-inflight',
    'power-of-two', 'weighted', 'sticky') or an already-built Policy."""
    if isinstance(spec, Policy):
        return spec
    cls = _POLICIES.get(str(spec))
    if cls is None:
        raise InferenceServerException(
            f"unknown balancing policy '{spec}' "
            f"(choose from {sorted(_POLICIES)})"
        )
    return cls()
