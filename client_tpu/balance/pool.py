"""Endpoint pool: N server replicas as one routable set.

The pool owns, per endpoint: a :class:`~client_tpu.resilience.CircuitBreaker`
(from a shared :class:`~client_tpu.resilience.CircuitBreakerRegistry`), a
health state (READY / NOT_READY / UNREACHABLE — the ``server_state()``
client verb's vocabulary), a membership *phase* (ACTIVE / PROBATION /
RETIRING), a routing weight, and a live inflight count.

Health is fed from two directions:

- **background readiness probes** (:meth:`EndpointPool.start_probes`): a
  daemon thread polls each endpoint's readiness with per-endpoint full
  jitter (a recovering fleet must not take synchronized probe bursts).
  Probes are what notice *drain* — a draining server still answers, with
  not-ready — and what bring a recovered endpoint back without burning a
  request on it.
- **per-request outcomes**: a successful response marks its endpoint READY
  immediately; a connection-level failure marks it UNREACHABLE (only while
  probing is active — without a prober nothing would ever un-mark it, so
  the circuit breaker alone gates the endpoint then).

Membership is *live* (:meth:`EndpointPool.update_endpoints`, the discovery
entry point — see balance/discovery.py):

- **added** endpoints enter PROBATION while a prober is armed and only take
  traffic once a readiness probe observes READY (without a prober they are
  admitted optimistically, like constructor endpoints);
- **removed** endpoints are gracefully RETIRED: no new leases, in-flight
  leases (including pinned streams) finish, then the endpoint is evicted;
- a **safety valve** never retires the last healthy endpoint — a flapping
  resolver cannot evict the only replica still serving.

Routing (:meth:`EndpointPool.lease`) filters to ACTIVE+READY endpoints
whose breaker admits an attempt (open circuits are skipped until their
half-open probe), asks the policy to pick, and returns a *lease* whose
``success()``/``failure()`` hooks feed the outcome back into inflight,
breaker, and health state — the contract
:func:`client_tpu.resilience.call_with_failover` drives.

All endpoint state is guarded by one pool lock; policies run under it (and
must not block — see policy.py).
"""

import random
import threading
import time

from client_tpu.balance.policy import make_policy
from client_tpu.resilience import (
    CircuitBreaker,
    CircuitBreakerRegistry,
    CircuitOpenError,
    NoHealthyEndpointError,
    _notify,
    _SerialDeliverer,
    is_connection_level,
)
from client_tpu.utils import (
    SERVER_NOT_READY,
    SERVER_READY,
    SERVER_UNREACHABLE,
)

__all__ = [
    "Endpoint",
    "EndpointPool",
    "Lease",
    "PHASE_ACTIVE",
    "PHASE_PROBATION",
    "PHASE_RETIRING",
]

# Membership lifecycle phases (orthogonal to the READY/NOT_READY/
# UNREACHABLE health state: phase is what the operator/resolver wants,
# state is what probes/outcomes observe).
PHASE_ACTIVE = "active"
PHASE_PROBATION = "probation"
PHASE_RETIRING = "retiring"

_VALID_STATES = (SERVER_READY, SERVER_NOT_READY, SERVER_UNREACHABLE)


class Endpoint:
    """One replica: identity + routed-state (mutated under the pool lock)."""

    def __init__(self, url, weight=1.0, breaker=None):
        self.url = str(url)
        self.weight = float(weight)
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            name=self.url
        )
        # Optimistic start: an unprobed endpoint is routable until a probe
        # or an outcome says otherwise (pessimistic start would blackhole
        # a pool constructed before its servers finish binding).
        self.state = SERVER_READY
        self.phase = PHASE_ACTIVE
        self.inflight = 0
        self.last_error = None
        # Gossiped digest-prefix summary (what this replica's cache tier
        # holds), fed by probes via set_summary; the prefix-aware policy
        # reads it.  Empty = no affinity signal, policies fall back.
        self.summary = frozenset()
        # Gossiped autoscaling pressure ({"queue_depth", "prefix_hot",
        # ...}), fed by probes via set_pressure; surfaced through
        # pressures() and the observer's on_endpoint_pressure hook so a
        # discovery source can scale on it.  Empty = never gossiped.
        self.pressure = {}
        # monotonic stamp of the last set_pressure delivery: pressures()
        # drops entries older than a few probe intervals so a dead
        # replica's final gossip cannot steer the autoscaler forever.
        # None = never gossiped.
        self.pressure_at = None
        # Probation ramp-up (slow start): stamped at promote time when the
        # pool has a rampup window; ramp_fraction() climbs floor -> 1 over
        # [ramp_started, ramp_started + ramp_span].
        self.ramp_started = None
        self.ramp_span = 0.0
        self.ramp_floor = 0.1
        # State-change delivery ordering: transitions are stamped under the
        # pool lock and delivered outside it with stale ones dropped, so a
        # preempted thread can never park the endpoint-state gauge on an
        # older value (same scheme as CircuitBreaker._deliver).
        self._state_seq = 0
        self._state_delivered = 0

    def __repr__(self):
        return (
            f"Endpoint({self.url!r}, state={self.state}, "
            f"phase={self.phase}, inflight={self.inflight}, "
            f"circuit={self.breaker.state})"
        )

    def ramp_fraction(self, now=None):
        """Slow-start traffic share in [ramp_floor, 1]: 1.0 when not
        ramping, else the elapsed fraction of the ramp window (floored so
        a freshly promoted replica gets SOME probe traffic — zero share
        would never exercise it).  Consumed ONLY by the pool's candidate
        thinning — the single ramp mechanism, applied before any policy
        runs, so weight-aware policies don't compound the penalty."""
        if self.ramp_started is None or self.ramp_span <= 0:
            return 1.0
        now = time.monotonic() if now is None else now
        frac = (now - self.ramp_started) / self.ramp_span
        if frac >= 1.0:
            self.ramp_started = None  # ramp complete: back to O(1) checks
            return 1.0
        return max(frac, self.ramp_floor)


class Lease:
    """One routed attempt on one endpoint.

    Exactly one of :meth:`success` / :meth:`failure` must be called to
    release the inflight slot and record the outcome (the failover loop in
    ``client_tpu.resilience`` does this).  ``key`` is the stable endpoint
    identity the loop excludes on retry; ``last_candidate`` is True when no
    other non-excluded healthy replica existed at pick time (so the loop
    backs off instead of hammering a wrapped rotation).
    """

    __slots__ = ("_pool", "endpoint", "key", "last_candidate", "_done")

    def __init__(self, pool, endpoint, last_candidate):
        self._pool = pool
        self.endpoint = endpoint
        self.key = endpoint.url
        self.last_candidate = last_candidate
        self._done = False

    @property
    def url(self):
        return self.endpoint.url

    def success(self):
        if not self._done:
            self._done = True
            self._pool._complete(self.endpoint, ok=True)

    def failure(self, exc=None, retryable=True):
        if not self._done:
            self._done = True
            self._pool._complete(
                self.endpoint, ok=False, exc=exc, retryable=retryable
            )

    def release(self):
        """Free the inflight slot WITHOUT health/breaker evidence — for
        leases whose outcome says nothing about the endpoint (a finished
        stream may end because the endpoint died; marking it READY would
        route new work at a corpse until the next probe)."""
        if not self._done:
            self._done = True
            self._pool._release(self.endpoint)


class EndpointPool:
    """Registry of replicas + health state machine + policy routing.

    Parameters
    ----------
    endpoints : iterable of url strings, ``(url, weight)`` pairs, or
        prebuilt :class:`Endpoint` objects.
    policy : policy name or Policy instance (see balance/policy.py).
    breakers : optional shared CircuitBreakerRegistry; one is created from
        ``failure_threshold``/``reset_timeout_s`` when absent.
    observer : optional hook object; any subset of ``on_route(url)``,
        ``on_failover(url)`` (a retryable failure rotated the request off
        this endpoint), ``on_endpoint_state(url, state)``,
        ``on_endpoint_phase(url, phase)``, ``on_membership(op, url)`` (op
        in add/retire/unretire/promote/retain/evict), and
        ``on_pool_size(active, probation, retiring)`` is called —
        ``client_tpu.serve.metrics.BalancerMetricsObserver`` feeds these
        into per-endpoint /metrics series.
    """

    def __init__(self, endpoints, policy="round-robin", breakers=None,
                 failure_threshold=5, reset_timeout_s=30.0, observer=None,
                 rampup_s=0.0, rampup_floor=0.1, rng=None):
        # Probation ramp-up (slow start): a PROBATION endpoint promoted to
        # ACTIVE takes traffic gradually over `rampup_s` seconds instead of
        # instantly absorbing a full 1/N share — a replica whose caches,
        # JIT executables, and connection pools are cold serves its first
        # requests slowest, and handing it full traffic at promote time
        # spikes tail latency exactly when the fleet just recovered.
        # 0.0 (default) disables; `rampup_floor` is the minimum share so a
        # ramping replica still sees some traffic from t=0.
        self.rampup_s = float(rampup_s)
        self.rampup_floor = float(rampup_floor)
        self._ramp_rng = rng if rng is not None else random.Random()
        if breakers is None:
            breakers = CircuitBreakerRegistry(
                failure_threshold=failure_threshold,
                reset_timeout_s=reset_timeout_s,
            )
        self.breakers = breakers
        self.observer = observer
        self._policy = make_policy(policy)
        self._lock = threading.Lock()
        self._endpoints = []
        for spec in endpoints:
            self._endpoints.append(self._build_endpoint(spec))
        # construction errors are programming errors, not the transient
        # retryable NoHealthyEndpointError routing raises
        if not self._endpoints:
            raise ValueError("endpoint pool constructed empty")
        seen = set()
        for endpoint in self._endpoints:
            if endpoint.url in seen:
                raise ValueError(
                    f"duplicate endpoint {endpoint.url!r} in pool"
                )
            seen.add(endpoint.url)
        # probe plumbing (armed by start_probes; _probe_loop reads these)
        self._probe = None
        self._probe_interval_s = 0.0
        self._prober = None
        self._stop = threading.Event()
        # Observer delivery: ordered, stale-dropping, and — crucially —
        # with NO pool lock held during the callback (an observer that
        # looks back at the pool, or whose delivery triggers another
        # transition, must never deadlock on a private delivery lock).
        self._deliverer = _SerialDeliverer()

    def _build_endpoint(self, spec):
        if isinstance(spec, Endpoint):
            return spec
        if isinstance(spec, (tuple, list)):
            url, weight = spec
            return Endpoint(url, weight, self.breakers.get(str(url)))
        return Endpoint(spec, 1.0, self.breakers.get(str(spec)))

    # -- introspection -------------------------------------------------------

    def __len__(self):
        with self._lock:
            return len(self._endpoints)

    def urls(self):
        with self._lock:
            return [e.url for e in self._endpoints]

    def endpoints(self):
        with self._lock:
            return list(self._endpoints)

    def states(self):
        with self._lock:
            return {e.url: e.state for e in self._endpoints}

    def phases(self):
        """{url: ACTIVE/PROBATION/RETIRING} membership view."""
        with self._lock:
            return {e.url: e.phase for e in self._endpoints}

    def snapshot(self):
        """Per-endpoint routing view: state, phase, inflight, circuit,
        weight."""
        with self._lock:
            return [
                {
                    "url": e.url,
                    "state": e.state,
                    "phase": e.phase,
                    "inflight": e.inflight,
                    "weight": e.weight,
                    "circuit": e.breaker.state,
                }
                for e in self._endpoints
            ]

    def _sizes_locked(self):
        active = probation = retiring = 0
        for e in self._endpoints:
            if e.phase == PHASE_ACTIVE:
                active += 1
            elif e.phase == PHASE_PROBATION:
                probation += 1
            else:
                retiring += 1
        return active, probation, retiring

    # -- health state machine ------------------------------------------------

    def _deliver_state(self, endpoint, state, seq):
        """Deliver one stamped state transition, dropping it if a newer one
        was already delivered (out-of-order delivery would wedge the
        endpoint-state gauge on a stale value forever, since changes only
        notify on transitions).  The staleness check runs in delivery
        order inside the deliverer; the observer call runs lock-free."""
        if seq is None:
            return

        def accept():
            if seq <= endpoint._state_delivered:
                return False
            endpoint._state_delivered = seq
            return True

        self._deliverer.post(
            lambda: _notify(
                self.observer, "on_endpoint_state", endpoint.url, state
            ),
            accept,
        )

    def _deliver_events(self, events):
        """Deliver a batch of membership/phase events in order, contiguous
        per batch, with no lock held during the callbacks (observers may
        look back at the pool)."""
        if not events:
            return

        def deliver():
            for method, args in events:
                _notify(self.observer, method, *args)

        self._deliverer.post(deliver)

    def set_state(self, url, state):
        """Record a health observation for *url* (probe or admin).  A
        READY observation on a PROBATION endpoint promotes it to ACTIVE —
        the readiness gate new discovery members pass before taking
        traffic."""
        if state not in _VALID_STATES:
            raise ValueError(f"unknown endpoint state {state!r}")
        transition = None
        events = []
        with self._lock:
            for endpoint in self._endpoints:
                if endpoint.url != url:
                    continue
                if endpoint.state != state:
                    endpoint.state = state
                    endpoint._state_seq += 1
                    transition = (endpoint, state, endpoint._state_seq)
                if (
                    state == SERVER_READY
                    and endpoint.phase == PHASE_PROBATION
                ):
                    endpoint.phase = PHASE_ACTIVE
                    if self.rampup_s > 0:
                        # slow start: the promoted replica's share climbs
                        # from rampup_floor to full over the window
                        endpoint.ramp_started = time.monotonic()
                        endpoint.ramp_span = self.rampup_s
                        endpoint.ramp_floor = self.rampup_floor
                    events.append(("on_membership", ("promote", url)))
                    events.append(("on_endpoint_phase", (url, PHASE_ACTIVE)))
                    events.append(("on_pool_size", self._sizes_locked()))
        if transition is not None:
            self._deliver_state(*transition)
        self._deliver_events(events)

    def set_weight(self, url, weight):
        with self._lock:
            for endpoint in self._endpoints:
                if endpoint.url == url:
                    endpoint.weight = float(weight)

    def set_summary(self, url, digests):
        """Install *url*'s gossiped cache-summary (an iterable of digest
        strings — ``fleet.chain_digests`` / response-cache keys).  Probes
        piggyback this: a ``probe(url)`` returning ``(state, digests)``
        updates health AND summary in one round trip, so cache-aware
        routing costs no extra probe traffic."""
        summary = frozenset(str(d) for d in digests)
        with self._lock:
            for endpoint in self._endpoints:
                if endpoint.url == url:
                    endpoint.summary = summary

    def summaries(self):
        """{url: frozenset(digests)} gossip view."""
        with self._lock:
            return {e.url: e.summary for e in self._endpoints}

    def set_pressure(self, url, pressure):
        """Install *url*'s gossiped autoscaling pressure (a mapping of
        numeric signals — ``FleetTier.local_summary()['pressure']``).
        Probes piggyback this as the third element of a ``(state,
        digests, pressure)`` result; the observer's
        ``on_endpoint_pressure`` hook exports it as the
        ``ctpu_fleet_pressure_*`` per-endpoint gauges."""
        pressure = dict(pressure or {})
        matched = False
        now = time.monotonic()
        with self._lock:
            for endpoint in self._endpoints:
                if endpoint.url == url:
                    endpoint.pressure = pressure
                    endpoint.pressure_at = now
                    matched = True
        if matched:
            # unknown urls (an in-flight probe completing after eviction)
            # must NOT notify: the observer would resurrect the evicted
            # endpoint's pressure gauges and nothing would ever remove
            # them again
            _notify(self.observer, "on_endpoint_pressure", url, pressure)

    # pressure entries older than this many probe intervals are stale:
    # a dead replica's last gossip must not steer the autoscaler forever
    PRESSURE_FRESH_INTERVALS = 3.0

    def pressures(self):
        """{url: pressure dict} autoscaling-signal view — what a
        discovery source (or the autoscaler) polls to scale the fleet on
        queue depth, KV occupancy and prefix-affinity pressure.  With a
        prober armed, an entry not refreshed within
        ``PRESSURE_FRESH_INTERVALS`` probe intervals reads as ``{}`` —
        same as never-gossiped — so a dead replica's final numbers age
        out instead of lingering at their last value."""
        now = time.monotonic()
        horizon = (
            self.PRESSURE_FRESH_INTERVALS * self._probe_interval_s
            if self._probe_interval_s > 0 else None
        )
        with self._lock:
            out = {}
            for e in self._endpoints:
                stale = (
                    horizon is not None
                    and e.pressure_at is not None
                    and now - e.pressure_at > horizon
                )
                out[e.url] = {} if stale else dict(e.pressure)
            return out

    # -- live membership (the discovery entry point) -------------------------

    def update_endpoints(self, specs):
        """Apply a new membership list (urls, ``(url, weight)`` pairs, or
        Endpoint objects) — the :mod:`client_tpu.balance.discovery` feed.

        - New endpoints enter PROBATION while a prober is armed (promoted
          by their first READY probe; see :meth:`set_state`), ACTIVE
          otherwise.
        - Endpoints absent from *specs* are RETIRED: excluded from routing
          immediately, evicted once their in-flight leases (and pinned
          streams) finish.
        - A RETIRING endpoint named again is un-retired in place.
        - Safety valve: if the update would leave no healthy (ACTIVE +
          READY) member, the last healthy endpoint slated for removal is
          retained instead of retired — a flapping resolver can never
          evict the only replica still serving.

        Raises ValueError on an empty or duplicate-bearing list (config
        mistakes, not transient routing conditions).  Returns a summary
        dict: {"added", "retired", "unretired", "retained", "evicted"}.
        """
        incoming = []
        for spec in specs:
            if isinstance(spec, Endpoint):
                incoming.append((spec.url, spec.weight))
            elif isinstance(spec, (tuple, list)):
                url, weight = spec
                incoming.append((str(url), float(weight)))
            else:
                incoming.append((str(spec), None))
        if not incoming:
            raise ValueError(
                "refusing to apply empty endpoint membership "
                "(a flapping resolver must not drain the pool)"
            )
        urls = [u for u, _ in incoming]
        if len(set(urls)) != len(urls):
            raise ValueError(f"duplicate endpoint in membership: {urls}")

        events = []
        summary = {
            "added": [], "retired": [], "unretired": [], "retained": [],
            "evicted": [],
        }
        with self._lock:
            current = {e.url: e for e in self._endpoints}
            wanted = set(urls)
            for url, weight in incoming:
                endpoint = current.get(url)
                if endpoint is None:
                    endpoint = Endpoint(
                        url,
                        1.0 if weight is None else weight,
                        self.breakers.get(url),
                    )
                    if self._probe is not None:
                        # unproven: takes traffic only after a READY probe
                        endpoint.phase = PHASE_PROBATION
                        endpoint.state = SERVER_NOT_READY
                    self._endpoints.append(endpoint)
                    summary["added"].append(url)
                    events.append(("on_membership", ("add", url)))
                    events.append(
                        ("on_endpoint_phase", (url, endpoint.phase))
                    )
                else:
                    if weight is not None:
                        endpoint.weight = weight
                    if endpoint.phase == PHASE_RETIRING:
                        # resolver flapped it back before eviction
                        endpoint.phase = PHASE_ACTIVE
                        summary["unretired"].append(url)
                        events.append(("on_membership", ("unretire", url)))
                        events.append(
                            ("on_endpoint_phase", (url, PHASE_ACTIVE))
                        )

            removals = [
                e for e in self._endpoints
                if e.url not in wanted and e.phase != PHASE_RETIRING
            ]
            # safety valve: never retire the last healthy endpoint
            survivors_healthy = any(
                e.url in wanted
                and e.phase == PHASE_ACTIVE
                and e.state == SERVER_READY
                for e in self._endpoints
            )
            if not survivors_healthy:
                keep = next(
                    (
                        e for e in removals
                        if e.phase == PHASE_ACTIVE
                        and e.state == SERVER_READY
                    ),
                    None,
                )
                if keep is not None:
                    removals = [e for e in removals if e is not keep]
                    summary["retained"].append(keep.url)
                    events.append(("on_membership", ("retain", keep.url)))
            for endpoint in removals:
                endpoint.phase = PHASE_RETIRING
                summary["retired"].append(endpoint.url)
                events.append(("on_membership", ("retire", endpoint.url)))
                events.append(
                    ("on_endpoint_phase", (endpoint.url, PHASE_RETIRING))
                )
            summary["evicted"] = self._evict_idle_locked(events)
            events.append(("on_pool_size", self._sizes_locked()))
        self._deliver_events(events)
        return summary

    def _evict_idle_locked(self, events):
        """Drop RETIRING endpoints with no in-flight work (caller holds
        the pool lock and delivers *events* after releasing it)."""
        evicted = []
        keep = []
        for endpoint in self._endpoints:
            if endpoint.phase == PHASE_RETIRING and endpoint.inflight <= 0:
                evicted.append(endpoint.url)
                events.append(("on_membership", ("evict", endpoint.url)))
            else:
                keep.append(endpoint)
        if evicted:
            self._endpoints[:] = keep
        return evicted

    # -- probes --------------------------------------------------------------

    def start_probes(self, probe, interval_s=2.0, rng=None):
        """Start the background readiness prober.

        ``probe(url)`` must return one of the three state constants (the
        clients' ``server_state()`` verb is exactly this shape) — or a
        ``(state, digests)`` tuple to piggyback the replica's cache-tier
        summary for prefix-aware routing, or ``(state, digests,
        pressure)`` to additionally carry its autoscaling pressure
        signals — and should bound its own
        transport timeout — a probe that can block forever wedges the
        whole pool's (serial) prober.  Exceptions count as
        UNREACHABLE.  Each endpoint is probed on its own full-jittered
        schedule (first probe at ``uniform(0, interval)``, then every
        ``uniform(interval/2, interval)``) so a fleet of replicas never
        takes a synchronized probe burst.  Returns True when this call
        armed the prober, False when one was already running;
        :meth:`close` stops it (and the pool can be re-armed afterwards).
        """
        with self._lock:
            if self._prober is not None:
                return False
            # Each prober generation gets ITS OWN stop event and probe fn
            # as thread args: a zombie prober whose close() join timed out
            # (stuck in a slow probe) still answers only to its own event
            # and can never adopt a re-armed generation's probe.
            stop = threading.Event()
            self._stop = stop
            self._probe = probe
            self._probe_interval_s = float(interval_s)
            prober = threading.Thread(
                target=self._probe_loop,
                args=(probe, stop, float(interval_s),
                      rng if rng is not None else random.Random()),
                name="endpoint-pool-probe", daemon=True,
            )
            self._prober = prober
        prober.start()
        return True

    def _probe_schedule(self, url, next_due, now, interval_s, rng,
                        first_sight):
        """Jittered next-probe time for *url* (full jitter on first sight
        spreads a whole fleet's probes inside one interval; steady-state
        periods stay jittered so endpoints never re-align)."""
        if first_sight:
            next_due[url] = now + rng.uniform(0.0, interval_s)
        else:
            next_due[url] = now + rng.uniform(interval_s / 2.0, interval_s)

    def _probe_loop(self, probe, stop, interval_s, rng):
        # Whole-pass guard (the BG-THREAD-CRASH shape, generalizing the
        # probe-arity fix): ANY escaped exception — a broken observer, a
        # hostile summary payload — would otherwise kill this thread and
        # freeze all health probing forever, silently.
        next_due = {}
        while not stop.is_set():
            try:
                if self._probe_pass(probe, stop, interval_s, rng, next_due):
                    return
            except Exception:
                if stop.wait(interval_s):
                    return

    def _probe_pass(self, probe, stop, interval_s, rng, next_due):
        """One full probe sweep + sleep; True when *stop* fired."""
        with self._lock:
            members = [
                e.url for e in self._endpoints
                if e.phase != PHASE_RETIRING
            ]
        now = time.monotonic()
        for url in members:
            if stop.is_set():
                return True
            due = next_due.get(url)
            if due is None:
                self._probe_schedule(
                    url, next_due, now, interval_s, rng, True
                )
                continue
            if due > now:
                continue
            try:
                state = probe(url)
            except Exception:
                state = SERVER_UNREACHABLE
            # probes may piggyback the replica's cache-tier gossip:
            # (state, digests) updates health AND routing affinity, and
            # (state, digests, pressure) additionally carries the
            # autoscaling signals — all in one round trip (see
            # set_summary/set_pressure).  Any OTHER tuple arity is a
            # malformed probe result and must degrade like a broken
            # state — an unpack error here would kill the prober thread
            # and freeze all health probing forever.
            summary = None
            pressure = None
            if isinstance(state, tuple):
                if len(state) == 2:
                    state, summary = state
                elif len(state) == 3:
                    state, summary, pressure = state
                else:
                    state = SERVER_UNREACHABLE
            if state not in _VALID_STATES:
                state = SERVER_UNREACHABLE  # a broken probe is no health
                summary = None
                pressure = None
            self.set_state(url, state)
            if summary is not None:
                self.set_summary(url, summary)
            if pressure is not None:
                self.set_pressure(url, pressure)
            self._probe_schedule(
                url, next_due, time.monotonic(), interval_s, rng, False
            )
        # forget departed endpoints so the schedule map cannot grow
        live = set(members)
        for url in list(next_due):
            if url not in live:
                del next_due[url]
        now = time.monotonic()
        delays = [max(due - now, 0.0) for due in next_due.values()]
        sleep_s = min(delays) if delays else interval_s
        return stop.wait(min(max(sleep_s, 0.001), interval_s))

    def close(self):
        with self._lock:
            prober = self._prober
            self._prober = None
            # Clear the probe so the outcome-driven UNREACHABLE marking in
            # _complete() stops too: with no prober left to recover an
            # endpoint, one transient failure must not remove it forever.
            self._probe = None
            stop = self._stop
        stop.set()
        if prober is not None:
            prober.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- routing -------------------------------------------------------------

    def _routable_locked(self):
        """Endpoints whose health AND membership admit new work (breaker
        gating happens per-pick, where half-open single-probe semantics
        live).  PROBATION members are unproven, RETIRING members are on
        their way out — neither takes new leases."""
        return [
            e for e in self._endpoints
            if e.state == SERVER_READY and e.phase == PHASE_ACTIVE
        ]

    def _thin_ramping_locked(self, candidates, request_ctx=None):
        """Probabilistically skip ramping (slow-start) endpoints so EVERY
        policy — not just weight-aware ones — honors the ramp: a replica at
        ramp fraction f stays in the candidate set with probability f.
        Never empties the set (a pool of only-ramping replicas still
        serves).  Sequence-bearing requests are exempt: the sticky policy
        treats a missing pinned replica as DEAD and forces a sequence
        restart (SequenceRestartError) — thinning a healthy ramping
        replica out from under its pinned sequences would fabricate
        restarts for the whole ramp window."""
        if self.rampup_s <= 0:
            return candidates
        if request_ctx and request_ctx.get("sequence_id"):
            return candidates
        now = time.monotonic()
        kept = [
            e for e in candidates
            if (f := e.ramp_fraction(now)) >= 1.0
            or self._ramp_rng.random() < f
        ]
        return kept or candidates

    def lease(self, excluded=(), request_ctx=None):
        """Route one attempt: returns a :class:`Lease` on a healthy,
        breaker-admitted endpoint, preferring ones not in *excluded*
        (the failover loop's already-tried set).  ``request_ctx`` is an
        optional dict of request attributes (model_name, sequence_id,
        sequence_start/end) content-aware policies key on — the sticky
        sequence policy routes with it.  Raises
        :class:`NoHealthyEndpointError` when nothing is routable.

        Breaker gating runs OUTSIDE the pool lock: ``before_attempt()``
        can deliver an OPEN→HALF_OPEN observer transition, and an observer
        that looks back at the pool (states/snapshot) under our lock would
        deadlock."""
        with self._lock:
            routable = self._routable_locked()
            if not routable:
                raise NoHealthyEndpointError(
                    f"no endpoint is routable: {self._describe_locked()}"
                )
            routable = self._thin_ramping_locked(routable, request_ctx)
            fresh = [e for e in routable if e.url not in excluded]
            candidates = fresh or routable  # wrap once every replica tried
            last_candidate = len(fresh) <= 1
        fell_back = False
        last_open = None
        while True:
            if not candidates:
                if not fell_back and fresh and len(fresh) < len(routable):
                    # every fresh candidate is circuit-blocked: fall back
                    # to the already-tried remainder before giving up
                    candidates = [e for e in routable if e.url in excluded]
                    fell_back = True
                    last_candidate = True
                    continue
                with self._lock:
                    description = self._describe_locked()
                raise NoHealthyEndpointError(
                    "every routable endpoint is behind an open circuit: "
                    f"{description}"
                ) from last_open
            with self._lock:
                endpoint = self._policy.pick(candidates, request_ctx)
            try:
                # half-open single-probe gate: at most one caller gets
                # through a cooled-down open circuit
                endpoint.breaker.before_attempt()
            except CircuitOpenError as exc:
                last_open = exc
                candidates = [e for e in candidates if e is not endpoint]
                continue
            with self._lock:
                endpoint.inflight += 1
            lease = Lease(self, endpoint, last_candidate)
            break
        _notify(self.observer, "on_route", lease.url)
        return lease

    def pick(self, request_ctx=None):
        """Policy pick WITHOUT lease accounting — for external assignment
        (e.g. binding perf workers to replicas).  Skips endpoints that are
        unhealthy, non-ACTIVE, or behind a currently-open circuit; raises
        :class:`NoHealthyEndpointError` when none qualify."""
        with self._lock:
            candidates = [
                e for e in self._routable_locked()
                if e.breaker.state != CircuitBreaker.OPEN
            ]
            if not candidates:
                raise NoHealthyEndpointError(
                    f"no endpoint is routable: {self._describe_locked()}"
                )
            candidates = self._thin_ramping_locked(candidates, request_ctx)
            return self._policy.pick(candidates, request_ctx)

    def _describe_locked(self):
        return ", ".join(
            f"{e.url}={e.state}/{e.phase}/{e.breaker.state}"
            for e in self._endpoints
        )

    # -- outcome accounting (Lease callbacks) --------------------------------

    def _release(self, endpoint):
        """Outcome-free inflight release (Lease.release)."""
        events = []
        with self._lock:
            endpoint.inflight = max(endpoint.inflight - 1, 0)
            self._maybe_evict_locked(endpoint, events)
        self._deliver_events(events)

    def _maybe_evict_locked(self, endpoint, events):
        """Evict a drained RETIRING endpoint the moment its last in-flight
        lease releases (caller holds the pool lock)."""
        if (
            endpoint.phase == PHASE_RETIRING
            and endpoint.inflight <= 0
            and any(e is endpoint for e in self._endpoints)
        ):
            self._endpoints[:] = [
                e for e in self._endpoints if e is not endpoint
            ]
            events.append(("on_membership", ("evict", endpoint.url)))
            events.append(("on_pool_size", self._sizes_locked()))

    def _complete(self, endpoint, ok, exc=None, retryable=True):
        transition = None
        events = []
        with self._lock:
            endpoint.inflight = max(endpoint.inflight - 1, 0)
            if ok:
                endpoint.last_error = None
                if endpoint.state != SERVER_READY:
                    endpoint.state = SERVER_READY
                    endpoint._state_seq += 1
                    transition = (endpoint, SERVER_READY, endpoint._state_seq)
            else:
                endpoint.last_error = exc
                # UNREACHABLE needs BOTH: a connection-level failure (an
                # answered 429/503 means the server is alive — overloaded
                # or draining, never "dead") and an active prober to bring
                # the endpoint back; with no prober the breaker's
                # open/half-open cycle is the sole (self-recovering) gate.
                if (
                    retryable
                    and is_connection_level(exc)
                    and self._probe is not None
                    and endpoint.state == SERVER_READY
                ):
                    endpoint.state = SERVER_UNREACHABLE
                    endpoint._state_seq += 1
                    transition = (
                        endpoint, SERVER_UNREACHABLE, endpoint._state_seq
                    )
            self._maybe_evict_locked(endpoint, events)
        # Breaker accounting outside the pool lock (the breaker has its
        # own).  A non-retryable application error means the endpoint
        # answered — evidence of health, never a circuit strike.
        if ok or not retryable:
            endpoint.breaker.record_success()
        else:
            endpoint.breaker.record_failure()
        if not ok and retryable:
            _notify(self.observer, "on_failover", endpoint.url)
        if transition is not None:
            self._deliver_state(*transition)
        self._deliver_events(events)
