"""Replicated clients: the existing client API over an EndpointPool.

:class:`ReplicatedClient` (sync, HTTP or gRPC) and
:class:`AsyncReplicatedClient` (asyncio, HTTP or gRPC) present the familiar
``InferenceServerClient`` surface — ``infer``, the health and metadata
verbs, the gRPC streaming entry points — but take a pool of endpoints in
place of one URL and route every request through it:

- each request (and each retry attempt) goes to a healthy replica picked
  by the pool's policy; a failed attempt's endpoint is excluded so the
  retry lands on a *different* replica (the failover hop is immediate
  while an untried healthy replica exists — see
  :func:`client_tpu.resilience.call_with_failover`);
- drained replicas (ServerReady→false, observed by the background
  readiness probes) stop receiving new work while their in-flight
  requests finish;
- open circuits are skipped until their half-open probe admits one
  attempt;
- with a ``resolver`` (see :mod:`client_tpu.balance.discovery`), pool
  membership tracks the live fleet: added replicas enter probation and
  take traffic once probed ready, removed ones retire gracefully;
- sequence workloads ride the ``sticky`` policy: the ``sequence_id`` /
  ``sequence_start`` / ``sequence_end`` kwargs flow to the policy as the
  request context, so every request of a sequence lands on one replica
  (and a dead replica surfaces
  :class:`~client_tpu.balance.policy.SequenceRestartError`);
- with a ``tracer``, the whole request is one client span: every attempt
  records its endpoint (the failover hop is visible as consecutive
  CLIENT_ATTEMPT_START events with different endpoints) and the W3C
  ``traceparent`` is propagated to whichever server serves each attempt,
  so client and server spans join under one trace id.

Streams are pinned: ``start_stream``/``stream_infer`` lease one healthy
endpoint for the stream's lifetime.  The *resilient* variants
(:meth:`ReplicatedClient.resilient_stream`,
:meth:`AsyncReplicatedClient.resilient_stream_infer`) survive mid-stream
replica death by reconnecting to a fresh replica and replaying only the
unacknowledged requests — see :mod:`client_tpu.balance.stream`.
"""

import asyncio
import threading

from client_tpu import resilience as _resilience
from client_tpu import tracing as _tracing
from client_tpu.balance.discovery import DiscoveryLoop, make_resolver
from client_tpu.balance.pool import EndpointPool
from client_tpu.balance.stream import ResilientStream, aio_resilient_stream
from client_tpu.utils import SERVER_READY, raise_error

__all__ = ["ReplicatedClient", "AsyncReplicatedClient"]

_DEFAULT_PROBE_INTERVAL_S = 2.0
_DEFAULT_DISCOVERY_INTERVAL_S = 30.0
# Background probes must be bounded: one black-holed endpoint would
# otherwise wedge the pool's serial prober thread forever.
_PROBE_TIMEOUT_S = 5.0


def _default_factory(transport, aio):
    if transport == "http":
        if aio:
            from client_tpu.http import aio as mod
        else:
            from client_tpu import http as mod
    elif transport == "grpc":
        if aio:
            from client_tpu.grpc import aio as mod
        else:
            from client_tpu import grpc as mod
    else:
        raise_error(
            f"unknown transport '{transport}' (choose 'http' or 'grpc')"
        )
    return mod.InferenceServerClient


def _as_pool(pool_or_urls, policy):
    if isinstance(pool_or_urls, EndpointPool):
        return pool_or_urls, False
    return EndpointPool(pool_or_urls, policy=policy), True


def _attempt_timeout_kwargs(transport, kwargs, timeout_s):
    """Cap the caller's per-request client timeout by the deadline-derived
    per-attempt budget, in each transport's vocabulary (gRPC:
    ``client_timeout``; HTTP: ``client_timeout_s``)."""
    if timeout_s is None:
        return kwargs
    key = "client_timeout" if transport == "grpc" else "client_timeout_s"
    combined = _resilience.combine_timeouts(kwargs.get(key), timeout_s)
    # floor: an expired budget must not become a zero/negative transport
    # timeout (all three transports reject those); the failover loop's
    # deadline check raises right after the fast-failing attempt
    kwargs[key] = max(combined, 1e-3)
    return kwargs


def _request_ctx(model_name, kwargs):
    """The routing context content-aware policies (sticky) key on."""
    params = kwargs.get("parameters") or {}
    return {
        "model_name": model_name,
        "sequence_id": kwargs.get("sequence_id", 0),
        "sequence_start": bool(kwargs.get("sequence_start", False)),
        "sequence_end": bool(kwargs.get("sequence_end", False)),
        # durable sequences replicate server-side state through the fleet
        # tier: the sticky policy remaps them SILENTLY on replica death
        # instead of raising SequenceRestartError
        "sequence_durable": bool(params.get("sequence_durable", False)),
    }


def _sequence_params(kwargs):
    """Fold the ``sequence_durable``/``sequence_step`` convenience kwargs
    into the request ``parameters`` dict (the transport clients pass
    parameters through verbatim; these two are engine-level sequence
    semantics, not transport kwargs)."""
    durable = kwargs.pop("sequence_durable", None)
    step = kwargs.pop("sequence_step", None)
    if durable is None and step is None:
        return kwargs
    params = dict(kwargs.get("parameters") or {})
    if durable is not None:
        params["sequence_durable"] = bool(durable)
    if step is not None:
        params["sequence_step"] = int(step)
    kwargs["parameters"] = params
    return kwargs


def _prefix_digests(model_name, inputs, kwargs, prefix_fn, block_size):
    """The ``prefix_digests`` routing hint for the prefix-aware policy.

    Priority: an explicit ``prefix_digests=`` kwarg, then a
    ``prefix_tokens=`` kwarg (token ids the caller already has), then
    the client's ``prefix_fn(model_name, inputs)`` tokenizer hook.
    Returns a digest list or None; tokens digest through
    ``client_tpu.serve.fleet.chain_digests`` (imported lazily — plain
    transport clients never pay for the serving stack)."""
    digests = kwargs.pop("prefix_digests", None)
    tokens = kwargs.pop("prefix_tokens", None)
    if digests is not None:
        return list(digests)
    if tokens is None and prefix_fn is not None:
        tokens = prefix_fn(model_name, inputs)
    if tokens is None:
        return None
    from client_tpu.serve.fleet import chain_digests

    return chain_digests(tokens, block_size)


def _probe_fn(transport, client_for):
    """A bounded ``probe(url)`` callable for EndpointPool.start_probes."""
    if transport == "grpc":
        return lambda url: client_for(url).server_state(
            client_timeout=_PROBE_TIMEOUT_S
        )
    return lambda url: client_for(url).server_state(
        timeout_s=_PROBE_TIMEOUT_S
    )


class ReplicatedClient:
    """Synchronous replica-set client (HTTP or gRPC transport).

    Parameters
    ----------
    pool : EndpointPool, or an iterable of endpoint URLs (a pool with
        *policy* is built around it and owned/closed by this client).
    transport : 'http' or 'grpc' — which client speaks to each replica.
    policy : balancing policy for a URL-built pool (ignored when an
        EndpointPool is passed; configure the pool directly then).
        ``"sticky"`` routes sequence workloads (see the module docstring).
    retry_policy : RetryPolicy governing attempts/backoff/deadline across
        the failover loop.  Default: one attempt per replica plus one
        (every replica gets a shot, then one wrapped retry).  The policy's
        own ``circuit_breaker`` is unused — breakers are per-endpoint,
        owned by the pool.
    tracer : optional ClientTracer; see the module docstring.
    probe_interval_s : readiness-probe period (None disables probing —
        drain then goes unnoticed until requests fail, and discovery
        additions skip probation).
    resolver : optional endpoint-discovery source — anything
        :func:`client_tpu.balance.discovery.make_resolver` accepts
        (a Resolver, a callable, a config-file path, or a static list).
        A DiscoveryLoop polling it every *discovery_interval_s* keeps the
        pool's membership live; resolver errors keep last-known-good.
    client_factory : ``factory(url, **client_kwargs) -> client`` override.
    client_kwargs : passed to every per-endpoint client constructor.
    """

    def __init__(self, pool, transport="http", policy="round-robin",
                 retry_policy=None, tracer=None,
                 probe_interval_s=_DEFAULT_PROBE_INTERVAL_S,
                 resolver=None,
                 discovery_interval_s=_DEFAULT_DISCOVERY_INTERVAL_S,
                 client_factory=None, prefix_fn=None, prefix_block_size=16,
                 **client_kwargs):
        self._pool, self._owns_pool = _as_pool(pool, policy)
        self._transport = transport
        self._factory = client_factory or _default_factory(transport, False)
        self._client_kwargs = client_kwargs
        # tokenizer-aware prefix routing: prefix_fn(model_name, inputs)
        # returns the request's prompt token ids; infer() digests them
        # into the prefix_digests routing ctx the prefix-aware policy
        # keys on (explicit prefix_digests=/prefix_tokens= kwargs win)
        self._prefix_fn = prefix_fn
        self._prefix_block_size = int(prefix_block_size)
        # Per-endpoint clients are created lazily: with live discovery the
        # membership outgrows whatever existed at construction.
        self._clients = {}
        self._clients_lock = threading.Lock()
        self._retry_policy = retry_policy or _resilience.RetryPolicy(
            max_attempts=len(self._pool) + 1
        )
        self._tracer = tracer
        self._stream_lease = None
        self._discovery = None
        # Whether close() must stop the pool's prober: always for a pool
        # we built; for a caller-provided pool only when WE armed probes
        # on it (they run through our clients, which close() closes).
        self._stop_pool = self._owns_pool
        if probe_interval_s:
            armed = self._pool.start_probes(
                _probe_fn(transport, self.client_for),
                interval_s=probe_interval_s,
            )
            self._stop_pool = self._stop_pool or armed
        if resolver is not None:
            self._discovery = DiscoveryLoop(
                self._pool, make_resolver(resolver),
                interval_s=discovery_interval_s,
            ).start()

    # -- lifecycle -----------------------------------------------------------

    @property
    def pool(self):
        return self._pool

    @property
    def discovery(self):
        """The DiscoveryLoop when a resolver was given (None otherwise)."""
        return self._discovery

    def close(self):
        if self._discovery is not None:
            self._discovery.close()
        if self._stream_lease is not None:
            self.stop_stream()
        if self._stop_pool:
            # stops the prober; a shared pool stays usable (its owner can
            # re-arm probes with start_probes)
            self._pool.close()
        with self._clients_lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for client in clients:
            try:
                client.close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- routing core --------------------------------------------------------

    def _route(self, excluded, request_ctx=None):
        return self._pool.lease(excluded, request_ctx)

    def _routed(self, verb, *args, **kwargs):
        """One management/metadata call, routed with failover.  On gRPC
        the deadline-derived per-attempt timeout caps each verb's
        ``client_timeout`` (every gRPC verb takes it); the HTTP verbs ride
        their client's pool-level timeouts, which bound them too."""

        def attempt(lease, timeout_s):
            call_kwargs = dict(kwargs)
            if self._transport == "grpc":
                _attempt_timeout_kwargs("grpc", call_kwargs, timeout_s)
            return getattr(self.client_for(lease.url), verb)(
                *args, **call_kwargs
            )

        return _resilience.call_with_failover(
            attempt, self._retry_policy, self._route
        )

    # -- inference -----------------------------------------------------------

    def infer(self, model_name, inputs, **kwargs):
        """One inference, routed across the replica set with failover.

        Accepts the underlying transport client's ``infer`` kwargs plus
        four replica-set extras: ``sequence_durable=``/``sequence_step=``
        (folded into the request parameters — durable sequences survive
        replica death through the fleet tier) and
        ``prefix_digests=``/``prefix_tokens=`` (the prefix-aware
        routing hint; a ``prefix_fn`` client hook computes it from the
        inputs when neither is given).  The sequence kwargs double as
        the routing context for the sticky policy (see the module
        docstring).

        Sequence requests trace under ONE pinned trace id per sequence
        (``ClientTracer`` context pinning): every step — including the
        failover retries after a replica death — joins a single trace,
        which is what lets traceview show a kill-mid-stream failover as
        one timeline spanning client and both replicas."""
        seq_id = kwargs.get("sequence_id", 0)
        context_key = ("sequence", seq_id) if seq_id else None
        with _tracing.client_span(
            self._tracer, model_name, context_key=context_key
        ) as trace:
            headers = dict(kwargs.pop("headers", None) or {})
            if trace is not None:
                headers["traceparent"] = trace.traceparent()
            kwargs = _sequence_params(kwargs)
            ctx = _request_ctx(model_name, kwargs)
            digests = _prefix_digests(
                model_name, inputs, kwargs, self._prefix_fn,
                self._prefix_block_size,
            )
            if digests:
                ctx["prefix_digests"] = digests

            def route(excluded):
                return self._route(excluded, ctx)

            def attempt(lease, timeout_s):
                call_kwargs = dict(kwargs)
                if headers:
                    call_kwargs["headers"] = headers
                _attempt_timeout_kwargs(self._transport, call_kwargs,
                                        timeout_s)
                with _tracing.attempt_span(trace, endpoint=lease.url):
                    return self.client_for(lease.url).infer(
                        model_name, inputs, **call_kwargs
                    )

            result = _resilience.call_with_failover(
                attempt, self._retry_policy, route
            )
            if (context_key is not None and kwargs.get("sequence_end")
                    and self._tracer is not None):
                # the sequence is over: a restarted id starts fresh
                self._tracer.release_context(context_key)
            return result

    # -- health --------------------------------------------------------------
    # "The service" is live/ready when ANY replica is; per-replica detail
    # comes from server_states() (direct probes) / states() (pool view).

    def is_server_live(self, **kwargs):
        return any(
            self._safe(self.client_for(url).is_server_live, **kwargs)
            for url in self._pool.urls()
        )

    def is_server_ready(self, **kwargs):
        return any(
            state == SERVER_READY
            for state in self.server_states(**kwargs).values()
        )

    def is_model_ready(self, model_name, **kwargs):
        return any(
            self._safe(self.client_for(url).is_model_ready, model_name,
                       **kwargs)
            for url in self._pool.urls()
        )

    def server_states(self, **kwargs):
        """{url: READY/NOT_READY/UNREACHABLE} — one live probe per replica,
        each bounded by the default probe timeout unless the caller passes
        their own (a black-holed replica must not hang the sweep)."""
        if not kwargs:
            key = (
                "client_timeout" if self._transport == "grpc" else "timeout_s"
            )
            kwargs = {key: _PROBE_TIMEOUT_S}
        return {
            url: self.client_for(url).server_state(**kwargs)
            for url in self._pool.urls()
        }

    def states(self):
        """The pool's current (probe/outcome-fed) health view."""
        return self._pool.states()

    @staticmethod
    def _safe(fn, *args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception:
            return False

    # -- metadata / management (routed with failover) ------------------------

    def get_server_metadata(self, *args, **kwargs):
        return self._routed("get_server_metadata", *args, **kwargs)

    def get_model_metadata(self, *args, **kwargs):
        return self._routed("get_model_metadata", *args, **kwargs)

    def get_model_config(self, *args, **kwargs):
        return self._routed("get_model_config", *args, **kwargs)

    def get_model_repository_index(self, *args, **kwargs):
        return self._routed("get_model_repository_index", *args, **kwargs)

    def get_inference_statistics(self, *args, **kwargs):
        return self._routed("get_inference_statistics", *args, **kwargs)

    def call(self, verb, *args, **kwargs):
        """Escape hatch: route any other client verb with failover.  For
        verbs with side effects on ONE replica (model load/unload, shm
        registration) address the per-endpoint client directly instead:
        ``client_for(url).load_model(...)``."""
        return self._routed(verb, *args, **kwargs)

    def client_for(self, url):
        """The underlying per-endpoint client (created on first use —
        discovery can add endpoints long after construction)."""
        with self._clients_lock:
            client = self._clients.get(url)
            if client is None:
                client = self._factory(url, **self._client_kwargs)
                self._clients[url] = client
            return client

    # -- streaming (gRPC): pinned to one healthy replica ---------------------

    def start_stream(self, callback, **kwargs):
        if self._transport != "grpc":
            raise_error("streaming requires the grpc transport")
        if self._stream_lease is not None:
            raise_error("cannot start another stream with one already active")
        lease = self._pool.lease()
        try:
            self.client_for(lease.url).start_stream(callback, **kwargs)
        except BaseException as exc:
            # the lease must never leak, whatever start_stream raised
            # (an Exception feeds the health/breaker machinery; anything
            # else releases outcome-free)
            if isinstance(exc, Exception):
                lease.failure(exc, self._retry_policy.retryable(exc))
            else:
                lease.release()
            raise
        self._stream_lease = lease

    def resilient_stream(self, callback, max_unacked=256, **kwargs):
        """A self-healing stream over the replica set: reconnects to a
        fresh healthy replica on connection-level stream death, replaying
        unacknowledged requests (see balance/stream.py).  Independent of
        the pinned ``start_stream`` slot; close the returned
        :class:`~client_tpu.balance.stream.ResilientStream` when done."""
        if self._transport != "grpc":
            raise_error("streaming requires the grpc transport")
        return ResilientStream(
            self, callback, max_unacked=max_unacked, **kwargs
        )

    def async_stream_infer(self, *args, **kwargs):
        if self._stream_lease is None:
            raise_error("stream not available, call start_stream() first")
        self.client_for(self._stream_lease.url).async_stream_infer(
            *args, **kwargs
        )

    def stop_stream(self, cancel_requests=False):
        lease = self._stream_lease
        if lease is None:
            return
        self._stream_lease = None
        try:
            self.client_for(lease.url).stop_stream(cancel_requests)
        finally:
            # outcome-free: a stream may end BECAUSE the endpoint died, so
            # releasing must not assert health (success would flip a
            # drained/unreachable endpoint back to READY)
            lease.release()


class _PinnedStream:
    """The aio pinned response stream, with a leak-proof lease.

    A bare ``async def`` generator with ``finally: lease.release()`` only
    releases once the body RUNS — a generator that is created, never
    iterated, and then ``aclose()``d (or abandoned) never enters its body
    and leaks the inflight slot forever.  This wrapper releases on
    exhaustion, on terminal error, and on ``aclose()`` regardless of
    whether iteration ever started."""

    def __init__(self, stream, lease):
        self._stream = stream
        self._lease = lease
        self._released = False

    def _release(self):
        if not self._released:
            self._released = True
            # outcome-free (see ReplicatedClient.stop_stream): the stream
            # may have ended because the endpoint died
            self._lease.release()

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self._stream.__anext__()
        except BaseException:
            # StopAsyncIteration (exhausted), a stream error, or a
            # cancellation: the pin is over either way
            self._release()
            raise

    async def aclose(self):
        self._release()
        aclose = getattr(self._stream, "aclose", None)
        if aclose is not None:
            await aclose()


class AsyncReplicatedClient:
    """asyncio replica-set client (HTTP or gRPC transport).

    Same routing semantics as :class:`ReplicatedClient`; per-endpoint
    clients are created lazily inside the running event loop, and health
    probing is on-demand (`await refresh_states()`) rather than a
    background thread — outcome-driven state still routes around dead
    replicas between refreshes.  Live membership comes from calling
    ``pool.update_endpoints()`` (or sharing a pool that a sync client's
    resolver keeps current): this client never spawns threads itself.
    """

    def __init__(self, pool, transport="http", policy="round-robin",
                 retry_policy=None, tracer=None, client_factory=None,
                 prefix_fn=None, prefix_block_size=16, **client_kwargs):
        self._pool, self._owns_pool = _as_pool(pool, policy)
        self._transport = transport
        self._factory = client_factory or _default_factory(transport, True)
        self._client_kwargs = client_kwargs
        self._prefix_fn = prefix_fn
        self._prefix_block_size = int(prefix_block_size)
        self._clients = {}
        self._retry_policy = retry_policy or _resilience.RetryPolicy(
            max_attempts=len(self._pool) + 1
        )
        self._tracer = tracer

    @property
    def pool(self):
        return self._pool

    def _client_for(self, url):
        client = self._clients.get(url)
        if client is None:
            client = self._factory(url, **self._client_kwargs)
            self._clients[url] = client
        return client

    async def close(self):
        if self._owns_pool:
            self._pool.close()
        for client in self._clients.values():
            try:
                await client.close()
            except Exception:
                pass

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    # -- routing core --------------------------------------------------------

    def _route(self, excluded, request_ctx=None):
        return self._pool.lease(excluded, request_ctx)

    async def _routed(self, verb, *args, **kwargs):
        # same per-attempt timeout handling as the sync client's _routed
        async def attempt(lease, timeout_s):
            call_kwargs = dict(kwargs)
            if self._transport == "grpc":
                _attempt_timeout_kwargs("grpc", call_kwargs, timeout_s)
            return await getattr(self._client_for(lease.url), verb)(
                *args, **call_kwargs
            )

        return await _resilience.acall_with_failover(
            attempt, self._retry_policy, self._route
        )

    # -- inference -----------------------------------------------------------

    async def infer(self, model_name, inputs, **kwargs):
        # sequence requests pin one trace id per sequence id (see the
        # sync client's infer for the rationale)
        seq_id = kwargs.get("sequence_id", 0)
        context_key = ("sequence", seq_id) if seq_id else None
        with _tracing.client_span(
            self._tracer, model_name, context_key=context_key
        ) as trace:
            headers = dict(kwargs.pop("headers", None) or {})
            if trace is not None:
                headers["traceparent"] = trace.traceparent()
            kwargs = _sequence_params(kwargs)
            ctx = _request_ctx(model_name, kwargs)
            digests = _prefix_digests(
                model_name, inputs, kwargs, self._prefix_fn,
                self._prefix_block_size,
            )
            if digests:
                ctx["prefix_digests"] = digests

            def route(excluded):
                return self._route(excluded, ctx)

            async def attempt(lease, timeout_s):
                call_kwargs = dict(kwargs)
                if headers:
                    call_kwargs["headers"] = headers
                _attempt_timeout_kwargs(self._transport, call_kwargs,
                                        timeout_s)
                with _tracing.attempt_span(trace, endpoint=lease.url):
                    return await self._client_for(lease.url).infer(
                        model_name, inputs, **call_kwargs
                    )

            result = await _resilience.acall_with_failover(
                attempt, self._retry_policy, route
            )
            if (context_key is not None and kwargs.get("sequence_end")
                    and self._tracer is not None):
                self._tracer.release_context(context_key)
            return result

    # -- health --------------------------------------------------------------

    async def server_states(self, **kwargs):
        """{url: state} — all replicas probed CONCURRENTLY, each bounded
        by the default probe timeout unless the caller passes their own."""
        if not kwargs:
            key = (
                "client_timeout" if self._transport == "grpc" else "timeout_s"
            )
            kwargs = {key: _PROBE_TIMEOUT_S}
        urls = self._pool.urls()
        states = await asyncio.gather(
            *(self._client_for(url).server_state(**kwargs) for url in urls)
        )
        return dict(zip(urls, states))

    async def refresh_states(self, **kwargs):
        """Probe every replica once and feed the results into the pool
        (the async analog of the sync client's background prober)."""
        states = await self.server_states(**kwargs)
        for url, state in states.items():
            self._pool.set_state(url, state)
        return states

    async def is_server_live(self, **kwargs):
        for url in self._pool.urls():
            try:
                if await self._client_for(url).is_server_live(**kwargs):
                    return True
            except Exception:
                pass
        return False

    async def is_server_ready(self, **kwargs):
        states = await self.server_states(**kwargs)
        return any(state == SERVER_READY for state in states.values())

    async def is_model_ready(self, model_name, **kwargs):
        for url in self._pool.urls():
            try:
                if await self._client_for(url).is_model_ready(
                    model_name, **kwargs
                ):
                    return True
            except Exception:
                pass
        return False

    def states(self):
        return self._pool.states()

    # -- metadata / management -----------------------------------------------

    async def get_server_metadata(self, *args, **kwargs):
        return await self._routed("get_server_metadata", *args, **kwargs)

    async def get_model_metadata(self, *args, **kwargs):
        return await self._routed("get_model_metadata", *args, **kwargs)

    async def get_model_config(self, *args, **kwargs):
        return await self._routed("get_model_config", *args, **kwargs)

    async def get_model_repository_index(self, *args, **kwargs):
        return await self._routed(
            "get_model_repository_index", *args, **kwargs
        )

    async def get_inference_statistics(self, *args, **kwargs):
        return await self._routed("get_inference_statistics", *args, **kwargs)

    async def call(self, verb, *args, **kwargs):
        return await self._routed(verb, *args, **kwargs)

    def client_for(self, url):
        return self._client_for(url)

    # -- streaming (gRPC aio): pinned to one healthy replica -----------------

    def stream_infer(self, inputs_iterator, **kwargs):
        """Bidirectional stream over ONE leased healthy replica; the lease
        is released when the response stream finishes or the caller
        ``aclose()``s the returned stream — including an un-iterated one
        (a bare generator's ``finally`` never runs for a body that never
        started, which used to leak the inflight slot)."""
        if self._transport != "grpc":
            raise_error("streaming requires the grpc transport")
        lease = self._pool.lease()
        try:
            stream = self._client_for(lease.url).stream_infer(
                inputs_iterator, **kwargs
            )
        except BaseException as exc:
            if isinstance(exc, Exception):
                lease.failure(exc, self._retry_policy.retryable(exc))
            else:
                lease.release()
            raise
        return _PinnedStream(stream, lease)

    def resilient_stream_infer(self, inputs_iterator, max_unacked=256,
                               **kwargs):
        """Self-healing twin of :meth:`stream_infer`: reconnects to a
        fresh healthy replica on connection-level stream death, replays
        unacknowledged requests, and dedupes duplicate responses by
        request id (see balance/stream.py)."""
        if self._transport != "grpc":
            raise_error("streaming requires the grpc transport")
        return aio_resilient_stream(
            self, inputs_iterator, max_unacked=max_unacked, **kwargs
        )
