"""Client-side replica set: health/circuit-aware load balancing.

Turns N independent KServe-v2 endpoints into one logical service for all
four clients (sync/aio × HTTP/gRPC):

- :class:`EndpointPool` — endpoint registry with per-endpoint circuit
  breaker, health state machine (fed by background readiness probes and
  per-request outcomes), routing weight, and live inflight count.
- Policies (:mod:`client_tpu.balance.policy`) — round-robin,
  least-inflight, power-of-two-choices, weighted — behind one
  ``pick(candidates, request_ctx)`` interface.
- :class:`ReplicatedClient` / :class:`AsyncReplicatedClient` — the
  existing client API over a pool: every request (and every retry
  attempt, which excludes the failed endpoint) routes to a different
  healthy replica, respecting drain and open circuits.

Built on the resilience layer (`client_tpu.resilience`:
``call_with_failover``, ``CircuitBreakerRegistry``) and observable
through the metrics (`serve.metrics.BalancerMetricsObserver`) and tracing
(endpoint-stamped CLIENT_ATTEMPT spans) surfaces.  See README
"Replication & load balancing".
"""

from client_tpu.balance.policy import (
    LeastInflight,
    Policy,
    PowerOfTwoChoices,
    RoundRobin,
    Weighted,
    make_policy,
)
from client_tpu.balance.pool import Endpoint, EndpointPool, Lease
from client_tpu.balance.replicated import (
    AsyncReplicatedClient,
    ReplicatedClient,
)

__all__ = [
    "Endpoint",
    "EndpointPool",
    "Lease",
    "Policy",
    "RoundRobin",
    "LeastInflight",
    "PowerOfTwoChoices",
    "Weighted",
    "make_policy",
    "ReplicatedClient",
    "AsyncReplicatedClient",
]
