"""Client-side replica set: self-healing, health/circuit-aware balancing.

Turns N independent KServe-v2 endpoints into one logical service for all
four clients (sync/aio × HTTP/gRPC):

- :class:`EndpointPool` — endpoint registry with per-endpoint circuit
  breaker, health state machine (fed by jittered background readiness
  probes and per-request outcomes), live membership
  (``update_endpoints``: probation for new replicas, graceful retire for
  removed ones, a safety valve for the last healthy endpoint), routing
  weight, and live inflight count.
- Discovery (:mod:`client_tpu.balance.discovery`) — pluggable
  :class:`Resolver` sources (static list, config-file watcher,
  DNS-style callable, TTL-honoring :class:`SrvResolver`) polled by a
  :class:`DiscoveryLoop` that feeds the pool; resolver errors keep
  last-known-good membership.
- Policies (:mod:`client_tpu.balance.policy`) — round-robin,
  least-inflight, power-of-two-choices, weighted, sticky (sequence-
  affine, with the :class:`SequenceRestartError` restart contract), and
  prefix-aware (cache-affinity over gossiped digest summaries) — behind
  one ``pick(candidates, request_ctx)`` interface.
- :class:`ReplicatedClient` / :class:`AsyncReplicatedClient` — the
  existing client API over a pool: every request (and every retry
  attempt, which excludes the failed endpoint) routes to a different
  healthy replica, respecting drain, probation/retire, and open circuits.
- :class:`ResilientStream` / ``resilient_stream_infer`` — replica-aware
  streaming reconnect: a mid-stream replica death hops the stream to a
  fresh replica, replaying only unacknowledged requests and deduping
  duplicate responses by request id.

Built on the resilience layer (`client_tpu.resilience`:
``call_with_failover``, ``CircuitBreakerRegistry``) and observable
through the metrics (`serve.metrics.BalancerMetricsObserver`) and tracing
(endpoint-stamped CLIENT_ATTEMPT spans) surfaces.  See README
"Replication & load balancing" and "Self-healing & discovery".
"""

from client_tpu.balance.discovery import (
    CallableResolver,
    ConfigFileResolver,
    DiscoveryLoop,
    Resolver,
    SrvResolver,
    StaticResolver,
    make_resolver,
)
from client_tpu.balance.policy import (
    LeastInflight,
    Policy,
    PowerOfTwoChoices,
    PrefixAware,
    RoundRobin,
    SequenceRestartError,
    Sticky,
    Weighted,
    make_policy,
)
from client_tpu.balance.pool import (
    PHASE_ACTIVE,
    PHASE_PROBATION,
    PHASE_RETIRING,
    Endpoint,
    EndpointPool,
    Lease,
)
from client_tpu.balance.replicated import (
    AsyncReplicatedClient,
    ReplicatedClient,
)
from client_tpu.balance.stream import ResilientStream, aio_resilient_stream

__all__ = [
    "Endpoint",
    "EndpointPool",
    "Lease",
    "PHASE_ACTIVE",
    "PHASE_PROBATION",
    "PHASE_RETIRING",
    "Policy",
    "RoundRobin",
    "LeastInflight",
    "PowerOfTwoChoices",
    "Weighted",
    "Sticky",
    "PrefixAware",
    "SequenceRestartError",
    "make_policy",
    "Resolver",
    "StaticResolver",
    "CallableResolver",
    "ConfigFileResolver",
    "SrvResolver",
    "make_resolver",
    "DiscoveryLoop",
    "ReplicatedClient",
    "AsyncReplicatedClient",
    "ResilientStream",
    "aio_resilient_stream",
]
