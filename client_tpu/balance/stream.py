"""Replica-aware streaming reconnect over the pinned gRPC stream.

PR 4 pinned every gRPC stream to one replica for life: a mid-stream
replica death was a client-visible stream error, full stop.  This module
makes streams *self-healing*:

- :class:`ResilientStream` (sync) wraps the pinned
  ``start_stream``/``async_stream_infer`` surface.  Every request is
  tracked by request id in a bounded replay buffer until its response
  arrives; on a **connection-level** stream death
  (:func:`client_tpu.resilience.is_connection_level` — the replica died
  or vanished, as opposed to answering an application error) the stream
  leases a fresh healthy replica from the pool, replays only the
  unacknowledged requests, and dedupes any duplicate responses by request
  id.  Application errors mid-stream (the server answered, with an error)
  still propagate to the user callback untouched.
- :func:`aio_resilient_stream` is the asyncio twin over
  ``stream_infer``'s async-iterator shape, yielding the familiar
  ``(result, error)`` pairs across reconnects.

Observability: with a tracer, the whole stream is ONE client span — each
connection is an endpoint-tagged CLIENT_ATTEMPT_START/END pair, so a
reconnect hop reads as consecutive attempts on different endpoints under
a single trace id (exactly how unary failover renders).  The pool
observer's ``on_stream_reconnect(url)`` / ``on_stream_replayed(url, n)``
hooks feed ``serve.metrics.BalancerMetricsObserver``'s reconnect and
replayed-request counters.

Delivery semantics: at-least-once to the *fleet* (a request the dead
replica processed without answering is replayed to the new one), exactly
once to the *callback* (duplicates deduped by request id).  Sequence
workloads should pair this with the sticky policy's restart contract —
replayed sequence state lives on the new replica only.
"""

import asyncio
import collections
import itertools
import os
import threading

from client_tpu.resilience import (
    NoHealthyEndpointError,
    _notify,
    is_connection_level,
)
from client_tpu.utils import InferenceServerException, raise_error

__all__ = ["ResilientStream", "aio_resilient_stream"]

# Acked-id memory, as a multiple of the replay-buffer bound: duplicates
# can only arise from replaying the still-unacked window, so a bounded
# multiple of it is enough dedupe history.
_ACK_MEMORY_FACTOR = 4


class ResilientStream:
    """Self-healing bidirectional stream over a replica set (sync gRPC).

    Built by :meth:`client_tpu.balance.ReplicatedClient.resilient_stream`;
    not constructed directly in normal use.

    Parameters
    ----------
    client : the owning ReplicatedClient (grpc transport).
    callback : ``callback(result, error)`` — the user's response callback,
        invoked exactly once per request id (duplicates after a replay are
        dropped) plus once per non-retryable terminal stream error.
    max_unacked : replay-buffer bound; :meth:`async_stream_infer` blocks
        (up to *send_timeout_s*) while this many requests are in flight
        unacknowledged.
    send_timeout_s : how long a send may wait for replay-buffer space.
    stream_kwargs : passed to every underlying ``start_stream`` call
        (stream_timeout, headers, compression_algorithm).
    """

    def __init__(self, client, callback, max_unacked=256,
                 send_timeout_s=30.0, **stream_kwargs):
        self._client = client
        self._user_callback = callback
        self._stream_kwargs = stream_kwargs
        self._pool = client.pool
        self._policy = client._retry_policy
        self._max_unacked = max(int(max_unacked), 1)
        self._send_timeout_s = float(send_timeout_s)
        self._cond = threading.Condition()
        # Dedicated per-connection transport client: the shared
        # per-endpoint clients host at most ONE stream each, so borrowing
        # them would collide with the pinned start_stream slot (and with
        # other ResilientStreams) — "independent" means its own channel.
        self._endpoint_client = None
        self._pending = collections.OrderedDict()  # rid -> (model, inputs, kw)
        self._acked = set()
        self._acked_order = collections.deque()
        self._generation = 0
        self._lease = None
        self._url = None
        self._closed = False
        self._failed = None
        self._rid_prefix = os.urandom(4).hex()
        self._rid_counter = itertools.count()
        self.reconnects = 0
        self.replayed = 0
        tracer = client._tracer
        self._tracer = tracer
        self._trace = tracer.sample("<stream>") if tracer is not None else None
        if self._trace is not None:
            self._trace.event("CLIENT_REQUEST_START")
        self._connect(excluded=())

    # -- introspection -------------------------------------------------------

    @property
    def url(self):
        """The currently pinned replica (None while reconnecting)."""
        with self._cond:
            return self._url

    @property
    def pending(self):
        """Unacknowledged request ids, oldest first."""
        with self._cond:
            return list(self._pending)

    @property
    def trace(self):
        return self._trace

    # -- connection management -----------------------------------------------

    def _connect(self, excluded):
        """Lease a healthy replica and open the underlying stream on it,
        rotating through the pool on connect failures (the retry policy
        bounds attempts and paces the backoff).  Replays the pending
        buffer when this is a reconnect."""
        excluded = list(excluded)
        attempt = 0
        while True:
            try:
                lease = self._pool.lease(tuple(excluded))
            except NoHealthyEndpointError:
                attempt += 1
                if attempt >= self._policy.max_attempts:
                    raise
                if self._wait_closed(self._policy.backoff_s(attempt)):
                    raise_error("resilient stream closed during reconnect")
                excluded = []  # the pool may have recovered: retry all
                continue
            with self._cond:
                if self._closed:
                    lease.release()
                    raise_error("resilient stream is closed")
                self._generation += 1
                generation = self._generation
            endpoint_client = self._client._factory(
                lease.url, **self._client._client_kwargs
            )
            callback = self._make_callback(generation, lease)
            try:
                endpoint_client.start_stream(callback, **self._stream_kwargs)
            except Exception as exc:
                self._close_client(endpoint_client)
                retryable = self._policy.retryable(exc)
                lease.failure(exc, retryable)
                attempt += 1
                # a start failure on ONE replica says nothing about the
                # others: rotate before giving up, whatever the class
                if attempt >= self._policy.max_attempts:
                    raise
                if lease.key not in excluded:
                    excluded.append(lease.key)
                continue
            with self._cond:
                self._lease = lease
                self._url = lease.url
                self._endpoint_client = endpoint_client
                replay = list(self._pending.items())
            if self._trace is not None:
                self._trace.event("CLIENT_ATTEMPT_START", endpoint=lease.url)
            if replay:
                sent = 0
                for rid, (model_name, inputs, kwargs) in replay:
                    try:
                        endpoint_client.async_stream_infer(
                            model_name, inputs, request_id=rid, **kwargs
                        )
                    except Exception:
                        # died again mid-replay: the new stream's error
                        # callback drives the next reconnect, which will
                        # replay the (still-buffered) remainder
                        break
                    sent += 1
                if sent:
                    self.replayed += sent
                    _notify(
                        self._pool.observer, "on_stream_replayed",
                        lease.url, sent,
                    )
            return

    @staticmethod
    def _close_client(endpoint_client):
        if endpoint_client is None:
            return
        try:
            endpoint_client.close()
        except Exception:
            pass

    def _wait_closed(self, timeout_s):
        """Backoff sleep that wakes early on close; True when closed."""
        with self._cond:
            return self._cond.wait_for(lambda: self._closed, timeout=timeout_s)

    def _make_callback(self, generation, lease):
        def callback(result, error):
            self._on_event(generation, lease, result, error)

        return callback

    # -- sending -------------------------------------------------------------

    def async_stream_infer(self, model_name, inputs, request_id="",
                           **kwargs):
        """Enqueue one request (the pinned surface's signature).  Assigns
        a request id when the caller passes none — ids are the replay and
        dedupe identity, so they must be unique per stream.  Returns the
        request id.  Blocks while the replay buffer is full."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: (
                    len(self._pending) < self._max_unacked
                    or self._closed
                    or self._failed is not None
                ),
                timeout=self._send_timeout_s,
            )
            if self._closed:
                raise_error("resilient stream is closed")
            if self._failed is not None:
                raise self._failed
            if not ok:
                raise_error(
                    f"replay buffer full: {len(self._pending)} requests "
                    "unacknowledged (server not responding?)"
                )
            rid = request_id or f"{self._rid_prefix}-{next(self._rid_counter)}"
            if rid in self._pending or rid in self._acked:
                raise_error(f"duplicate request id {rid!r} on this stream")
            self._pending[rid] = (model_name, inputs, dict(kwargs))
            endpoint_client = (
                self._endpoint_client if self._url is not None else None
            )
        if endpoint_client is None:
            return rid  # reconnecting: the replay will carry it
        try:
            endpoint_client.async_stream_infer(
                model_name, inputs, request_id=rid, **kwargs
            )
        except Exception as exc:
            if self._sendable_later(exc):
                # the stream died under us: leave the request buffered —
                # the in-flight reconnect replays it
                return rid
            with self._cond:
                self._pending.pop(rid, None)
                self._cond.notify_all()
            raise
        return rid

    @staticmethod
    def _sendable_later(exc):
        """Whether a failed send is a stream-death race (buffer + replay)
        rather than a per-request error (surface to the caller)."""
        if is_connection_level(exc):
            return True
        if not isinstance(exc, InferenceServerException):
            return False
        text = str(exc)
        # the two shapes a send races a stream death into: the stream
        # object flipped inactive, or stop_stream already cleared it
        return "stream is closed" in text or "stream not available" in text

    # -- response/error handling ---------------------------------------------

    def _ack_locked(self, rid):
        """Record one answered request id; False when it is a duplicate
        (already answered before a replay re-sent it)."""
        if not rid:
            return True  # id-less response: nothing to dedupe against
        if rid in self._acked:
            return False
        self._acked.add(rid)
        self._acked_order.append(rid)
        while len(self._acked_order) > _ACK_MEMORY_FACTOR * self._max_unacked:
            self._acked.discard(self._acked_order.popleft())
        self._pending.pop(rid, None)
        self._cond.notify_all()
        return True

    def _on_event(self, generation, lease, result, error):
        rid = ""
        if result is not None:
            try:
                rid = result.get_response().id
            except Exception:
                rid = ""
        with self._cond:
            if self._closed or generation != self._generation:
                return  # a dead generation's tail: already superseded
            if error is not None and is_connection_level(error):
                # connection-level stream death: reconnect off this thread
                # (it is the dying stream's handler thread; the reconnect
                # must outlive it and may join it via stop_stream)
                threading.Thread(
                    target=self._reconnect,
                    args=(generation, lease, error),
                    name="resilient-stream-reconnect", daemon=True,
                ).start()
                return
            if not self._ack_locked(rid):
                return  # duplicate response after a replay
        # user callback outside the lock: it may send more requests
        self._user_callback(result=result, error=error)

    def _reconnect(self, generation, dead_lease, error):
        with self._cond:
            if self._closed or generation != self._generation:
                return
            self._generation += 1  # invalidate the dead stream's tail now
            self._lease = None
            dead_url = self._url
            dead_client = self._endpoint_client
            self._url = None
            self._endpoint_client = None
            self.reconnects += 1
        dead_lease.failure(error, retryable=True)
        if self._trace is not None:
            self._trace.event("CLIENT_ATTEMPT_END", endpoint=dead_url)
        _notify(self._pool.observer, "on_stream_reconnect", dead_url)
        if dead_client is not None:
            try:
                # joins the finished handler thread, then drops the channel
                dead_client.stop_stream(cancel_requests=True)
            except Exception:
                pass
            self._close_client(dead_client)
        try:
            self._connect(excluded=(dead_url,))
        except Exception as exc:  # terminal: no replica took the stream
            with self._cond:
                if self._closed:
                    return
                self._failed = exc
                self._cond.notify_all()
            self._user_callback(result=None, error=exc)

    # -- lifecycle -----------------------------------------------------------

    def close(self, cancel_requests=False):
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._generation += 1
            lease, url = self._lease, self._url
            endpoint_client = self._endpoint_client
            self._lease = None
            self._url = None
            self._endpoint_client = None
            self._cond.notify_all()
        if endpoint_client is not None:
            try:
                endpoint_client.stop_stream(cancel_requests)
            except Exception:
                pass
            self._close_client(endpoint_client)
        if lease is not None:
            # outcome-free: the stream ending says nothing about health
            lease.release()
        if self._trace is not None:
            self._trace.event("CLIENT_ATTEMPT_END", endpoint=url)
            self._trace.event("CLIENT_REQUEST_END")
            self._tracer.complete(self._trace)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def aio_resilient_stream(client, inputs_iterator, max_unacked=256,
                         **stream_kwargs):
    """Async twin of :class:`ResilientStream` over the aio
    ``stream_infer`` shape: maps an async iterator of ``infer``-kwargs
    dicts onto a replica-pinned bidirectional stream and yields
    ``(InferResult, error)`` pairs — reconnecting to a fresh healthy
    replica on connection-level stream death, replaying unacknowledged
    requests, and deduping duplicate responses by request id.

    Abandonment-safe: ``aclose()`` on the returned generator releases the
    lease and cancels the request pump even mid-reconnect.
    """
    policy = client._retry_policy
    pool = client.pool
    tracer = client._tracer
    bound = max(int(max_unacked), 1)

    async def _generator():
        pending = collections.OrderedDict()  # rid -> kwargs dict
        acked = set()
        acked_order = collections.deque()
        rid_prefix = os.urandom(4).hex()
        rid_counter = itertools.count()
        queue = asyncio.Queue(maxsize=bound)
        space = asyncio.Event()
        done_sentinel = object()
        state = {"source_done": False, "invalid": None}
        trace = tracer.sample("<stream>") if tracer is not None else None
        if trace is not None:
            trace.event("CLIENT_REQUEST_START")

        async def pump():
            async for kwargs in inputs_iterator:
                await queue.put(dict(kwargs))
            await queue.put(done_sentinel)

        pump_task = asyncio.ensure_future(pump())

        def feeder(replay):
            async def _requests():
                for kwargs in replay:
                    yield kwargs
                while not state["source_done"]:
                    while len(pending) >= bound:
                        space.clear()
                        await space.wait()  # acks free replay-buffer space
                    item = await queue.get()
                    if item is done_sentinel:
                        state["source_done"] = True
                        return
                    rid = item.get("request_id") or (
                        f"{rid_prefix}-{next(rid_counter)}"
                    )
                    if rid in pending or rid in acked:
                        # ids are the replay/dedupe identity: a reused one
                        # would silently clobber the replay buffer and eat
                        # the second response (the sync twin rejects too).
                        # Recorded before raising: grpc wraps feeder
                        # exceptions, so the response loop re-raises ours.
                        state["invalid"] = InferenceServerException(
                            f"duplicate request id {rid!r} on this stream"
                        )
                        raise state["invalid"]
                    item["request_id"] = rid
                    # record-before-yield, with no await between: a
                    # cancellation (stream death) can never lose a pulled
                    # request — it is already in the replay buffer
                    pending[rid] = item
                    yield item

            return _requests()

        lease = None
        attempt = 0
        excluded = ()
        try:
            while True:
                try:
                    lease = pool.lease(tuple(excluded))
                except NoHealthyEndpointError:
                    lease = None
                    attempt += 1
                    if attempt >= policy.max_attempts:
                        raise
                    await asyncio.sleep(policy.backoff_s(attempt))
                    excluded = ()
                    continue
                url = lease.url
                replay = list(pending.values())
                if trace is not None:
                    trace.event("CLIENT_ATTEMPT_START", endpoint=url)
                if replay:  # non-empty only on a reconnect
                    _notify(
                        pool.observer, "on_stream_replayed", url, len(replay)
                    )
                stream = client.client_for(url).stream_infer(
                    feeder(replay), **stream_kwargs
                )
                try:
                    async for result, error in stream:
                        # progress on this connection resets the reconnect
                        # budget: a long-lived stream gets a fresh attempt
                        # allowance per independent replica death
                        attempt = 0
                        rid = ""
                        if result is not None:
                            try:
                                rid = result.get_response().id
                            except Exception:
                                rid = ""
                        if rid:
                            if rid in acked:
                                continue  # duplicate after a replay
                            acked.add(rid)
                            acked_order.append(rid)
                            while len(acked_order) > (
                                _ACK_MEMORY_FACTOR * bound
                            ):
                                acked.discard(acked_order.popleft())
                            pending.pop(rid, None)
                            space.set()
                        yield result, error
                except asyncio.CancelledError:
                    # grpc.aio cancels the call locally when the request
                    # iterator raises: surface OUR validation error then;
                    # a genuine consumer cancellation propagates untouched
                    if state["invalid"] is not None:
                        lease.release()
                        lease = None
                        raise state["invalid"] from None
                    raise
                except Exception as exc:
                    if state["invalid"] is not None:
                        # caller-input validation failure, not an endpoint
                        # problem: surface OUR error, no health strike
                        lease.release()
                        lease = None
                        raise state["invalid"] from exc
                    if not (
                        is_connection_level(exc) and policy.retryable(exc)
                    ):
                        lease.failure(exc, retryable=False)
                        lease = None
                        raise
                    # connection-level stream death: hop replicas
                    lease.failure(exc, retryable=True)
                    lease = None
                    if trace is not None:
                        trace.event("CLIENT_ATTEMPT_END", endpoint=url)
                    _notify(pool.observer, "on_stream_reconnect", url)
                    attempt += 1
                    if attempt >= policy.max_attempts:
                        raise
                    excluded = (url,)
                    space.set()  # wake a feeder parked on a full buffer
                    continue
                # stream ended normally (source exhausted, server closed)
                lease.release()
                lease = None
                if trace is not None:
                    trace.event("CLIENT_ATTEMPT_END", endpoint=url)
                return
        finally:
            pump_task.cancel()
            try:
                await pump_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            if lease is not None:
                lease.release()
            if trace is not None:
                trace.event("CLIENT_REQUEST_END")
                tracer.complete(trace)

    return _generator()
