"""Live endpoint discovery: resolvers feeding EndpointPool.update_endpoints.

A *resolver* answers "what replicas exist right now?" — the source of
truth a fleet actually has (a config file an operator edits, a DNS-style
lookup, a service-registry callable).  The :class:`DiscoveryLoop` polls
one and applies the answer to a live pool:

- resolved membership goes through
  :meth:`~client_tpu.balance.pool.EndpointPool.update_endpoints` (new
  endpoints enter probation, removed ones retire gracefully, the last
  healthy endpoint is never evicted);
- a resolver ERROR keeps the last-known-good membership — a registry
  outage must not look like a fleet-wide scale-down (the loop records the
  error and keeps serving on what it last saw).

Resolvers return an iterable of endpoint specs in the pool's vocabulary:
url strings or ``(url, weight)`` pairs.

This module is stdlib-only and thread-safe where it needs to be; the
loop's poller is a daemon thread, and :meth:`DiscoveryLoop.refresh_now`
gives tests and CLIs a synchronous poke.
"""

import json
import threading
import time

__all__ = [
    "Resolver",
    "StaticResolver",
    "CallableResolver",
    "ConfigFileResolver",
    "SrvResolver",
    "make_resolver",
    "DiscoveryLoop",
]


class Resolver:
    """Interface: :meth:`resolve` returns the current endpoint specs
    (url strings or ``(url, weight)`` pairs).  Raise on failure — the
    discovery loop treats an exception as "keep last-known-good", never
    as an empty fleet."""

    def resolve(self):
        raise NotImplementedError


class StaticResolver(Resolver):
    """A fixed list (the no-discovery degenerate case, useful to unify
    code paths and tests)."""

    def __init__(self, endpoints):
        self._endpoints = [
            tuple(e) if isinstance(e, (tuple, list)) else str(e)
            for e in endpoints
        ]

    def resolve(self):
        return list(self._endpoints)


class CallableResolver(Resolver):
    """Wrap any ``fn() -> endpoint specs`` (a DNS lookup, a service
    registry client, a test harness mutating membership)."""

    def __init__(self, fn):
        self._fn = fn

    def resolve(self):
        return self._fn()


class ConfigFileResolver(Resolver):
    """Membership from a config file an operator (or orchestrator) edits.

    Two formats, sniffed per read:

    - JSON: a list of url strings or ``[url, weight]`` pairs, or an
      object ``{"endpoints": [...]}``;
    - plain text: one endpoint per line, ``url`` or ``url weight``,
      ``#`` comments and blank lines ignored.

    Reads the file on every :meth:`resolve` (discovery intervals are
    seconds; an mtime cache would only save a stat).  A missing or
    unparseable file raises — the loop keeps last-known-good.
    """

    def __init__(self, path):
        self.path = str(path)

    def resolve(self):
        with open(self.path, "r", encoding="utf-8") as f:
            text = f.read()
        stripped = text.lstrip()
        if stripped.startswith(("[", "{")):
            data = json.loads(stripped)
            if isinstance(data, dict):
                data = data["endpoints"]
            return [
                (str(e[0]), float(e[1]))
                if isinstance(e, (list, tuple)) else str(e)
                for e in data
            ]
        specs = []
        for line in text.splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) == 1:
                specs.append(parts[0])
            else:
                specs.append((parts[0], float(parts[1])))
        return specs


class SrvResolver(Resolver):
    """DNS ``SRV``-style resolution honoring record TTLs.

    ``lookup()`` answers like an SRV query: an iterable of records, each
    a url string, a ``(url, weight)`` pair, or a ``(url, weight,
    ttl_s)`` triple (target + weight + per-record TTL).  Two behaviors a
    plain :class:`CallableResolver` cannot give a fleet:

    - **TTL caching**: :meth:`resolve` serves the cached answer until
      the SMALLEST record TTL expires (records without one use
      ``default_ttl_s``; ``min_ttl_s`` floors a zero/garbage TTL so a
      misconfigured zone cannot turn discovery into a query-per-request
      hot loop), then re-resolves;
    - **stale-on-error**: a lookup failure AFTER a successful resolution
      serves the last-known-good answer and re-arms a retry after
      ``min_ttl_s`` — a registry outage must not look like a fleet-wide
      scale-down.  Only an initial failure, with nothing cached yet,
      raises (the DiscoveryLoop then keeps ITS last-known-good).

    ``resolutions``/``errors``/``last_error`` count live behavior for
    tests and ops.
    """

    def __init__(self, lookup, default_ttl_s=30.0, min_ttl_s=1.0,
                 time_fn=time.monotonic):
        self._lookup = lookup
        self.default_ttl_s = float(default_ttl_s)
        self.min_ttl_s = float(min_ttl_s)
        self._time = time_fn
        self._lock = threading.Lock()
        self._cached = None
        self._expiry = 0.0
        self.resolutions = 0
        self.errors = 0
        self.last_error = None

    def _parse(self, records):
        specs = []
        ttls = []
        for record in records:
            if isinstance(record, (tuple, list)):
                url = str(record[0])
                weight = float(record[1]) if len(record) > 1 else 1.0
                if len(record) > 2 and record[2] is not None:
                    ttls.append(float(record[2]))
                specs.append((url, weight))
            else:
                specs.append(str(record))
        ttl = min(ttls) if ttls else self.default_ttl_s
        return specs, max(ttl, self.min_ttl_s)

    def resolve(self):
        now = self._time()
        with self._lock:
            if self._cached is not None and now < self._expiry:
                return list(self._cached)
        try:
            records = list(self._lookup())
        except Exception as exc:  # noqa: BLE001 - stale-on-error
            with self._lock:
                self.errors += 1
                self.last_error = exc
                if self._cached is not None:
                    # serve stale; retry after the floor, not the full
                    # TTL (the outage should be re-probed promptly)
                    self._expiry = now + self.min_ttl_s
                    return list(self._cached)
            raise
        specs, ttl = self._parse(records)
        with self._lock:
            self._cached = specs
            self._expiry = now + ttl
            self.resolutions += 1
        return list(specs)


def make_resolver(spec):
    """Resolver from a Resolver, a callable, a path string, or a list."""
    if isinstance(spec, Resolver):
        return spec
    if callable(spec):
        return CallableResolver(spec)
    if isinstance(spec, str):
        return ConfigFileResolver(spec)
    return StaticResolver(spec)


class DiscoveryLoop:
    """Poll a resolver and keep a pool's membership current.

    Parameters
    ----------
    pool : the live :class:`~client_tpu.balance.pool.EndpointPool`.
    resolver : anything :func:`make_resolver` accepts.
    interval_s : polling period (the poller thread is a daemon).
    on_update : optional ``fn(summary)`` called after each APPLIED update
        (the dict ``update_endpoints`` returns) — logging/test hook.

    Error containment: a resolver exception (or a membership the pool
    rejects, e.g. an empty list) leaves the pool on its last-known-good
    membership; the loop counts it (:attr:`errors`, :attr:`last_error`)
    and keeps polling.
    """

    def __init__(self, pool, resolver, interval_s=30.0, on_update=None):
        self.pool = pool
        self.resolver = make_resolver(resolver)
        self.interval_s = float(interval_s)
        self.on_update = on_update
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self.updates = 0
        self.errors = 0
        self.last_error = None

    def refresh_now(self):
        """One synchronous resolve+apply.  Returns the update summary, or
        None when the resolver (or the pool) rejected this round — the
        pool keeps its last-known-good membership either way."""
        try:
            specs = list(self.resolver.resolve())
            summary = self.pool.update_endpoints(specs)
        except Exception as exc:  # noqa: BLE001 - containment is the point
            with self._lock:
                self.errors += 1
                self.last_error = exc
            return None
        with self._lock:
            self.updates += 1
        if self.on_update is not None:
            try:
                self.on_update(summary)
            except Exception:
                pass
        return summary

    def start(self):
        with self._lock:
            if self._thread is not None:
                return self
            stop = threading.Event()
            self._stop = stop
            thread = threading.Thread(
                target=self._run, args=(stop,),
                name="endpoint-discovery", daemon=True,
            )
            self._thread = thread
        thread.start()
        return self

    def _run(self, stop):
        # refresh_now() contains its own errors, but the loop body still
        # sits under a guard (the BG-THREAD-CRASH shape): a poller thread
        # that dies silently freezes fleet membership forever
        while not stop.is_set():
            try:
                self.refresh_now()
            except Exception:  # pragma: no cover - defensive
                pass
            if stop.wait(self.interval_s):
                return

    def close(self):
        with self._lock:
            thread = self._thread
            self._thread = None
            stop = self._stop
        stop.set()
        if thread is not None:
            thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
