"""Shims over jax API renames, shared by every kernel/mesh module.

The repo is written against the current jax surface; older releases in
some images spell two things differently:

- ``pltpu.CompilerParams`` was ``pltpu.TPUCompilerParams`` (same kwargs
  for everything we pass — ``dimension_semantics``);
- ``jax.shard_map`` lived at ``jax.experimental.shard_map.shard_map``
  with the replication checker spelled ``check_rep`` instead of
  ``check_vma``.

One home for both so the next rename is a one-file fix instead of a
hunt across every pallas kernel.
"""

import jax
from jax.experimental.pallas import tpu as _pltpu

# Pallas TPU compiler-params class under whichever name this jax has.
CompilerParams = getattr(
    _pltpu, "CompilerParams", getattr(_pltpu, "TPUCompilerParams", None)
)


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across the rename.  On the legacy path the
    ``check_rep`` checker is SKIPPED: it predates the varying-axis (vma)
    semantics this code is written against and rejects valid programs
    (e.g. a causal ring's ``lax.cond`` under grad — jax's own error text
    suggests ``check_rep=False``); it is static validation only, never
    part of the compiled program."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)
