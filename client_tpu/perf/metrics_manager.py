"""Server metrics scraping during measurement.

Parity with the reference MetricsManager (reference
src/c++/perf_analyzer/metrics_manager.h:44-91): a background thread polls
the server's Prometheus ``/metrics`` on an interval and keeps per-window
snapshots; the profiler merges them into each load level's summary.  The
counters of interest are the TPU ones this framework's server exposes
(``ctpu_tpu_memory_*``) plus the inference counters — the
``nv_gpu_utilization`` analog set.
"""

import threading
import urllib.request

import numpy as np

from client_tpu.analysis.witness import witness_shared
from client_tpu.utils import escape_label


def parse_prometheus(text):
    """Prometheus text format -> {metric_name: [(labels_str, value), ...]}."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value_part = line.rsplit(" ", 1)
            value = float(value_part)
        except ValueError:
            continue
        if "{" in name_part:
            name, labels = name_part.split("{", 1)
            labels = "{" + labels
        else:
            name, labels = name_part, ""
        out.setdefault(name, []).append((labels, value))
    return out


def local_device_snapshot():
    """Device gauges read directly from the local PJRT runtime
    (jax.local_devices()[i].memory_stats()) — the telemetry source of last
    resort when the *server* under test exposes no TPU gauges (any
    third-party KServe server; reference metrics_manager.h:44-91 has the
    same blind spot for non-Triton servers).  Only meaningful when the perf
    process is colocated with the chip.  Returns {} off-device."""
    out = {}
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        labels = f'{{device="{escape_label(d.id)}",source="local"}}'
        used = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit"
        )
        peak = stats.get("peak_bytes_in_use")
        if used is not None:
            out.setdefault("ctpu_tpu_memory_used_bytes", []).append(
                (labels, float(used))
            )
        if limit is not None:
            out.setdefault("ctpu_tpu_memory_total_bytes", []).append(
                (labels, float(limit))
            )
        if peak is not None:
            out.setdefault("ctpu_tpu_memory_peak_bytes", []).append(
                (labels, float(peak))
            )
    return out


class DeviceUtilizationProbe:
    """Server-independent device utilization estimator.

    Dispatches a microscopic jitted kernel on the LOCAL chip and times its
    completion: when another process's work occupies the device, the probe
    queues behind it, so probe latency beyond the idle baseline samples the
    device's queue delay directly.  This trusts nothing the server under
    test reports — the blind spot the reference has for non-Triton servers
    (its nv_gpu_utilization comes from Triton's own /metrics;
    metrics_manager.h:44-91).

    Per sample: queue delay in us, and a busy flag (latency >
    busy_factor × idle baseline).  A window of samples summarizes as
    ``ctpu_probe_utilization_pct`` = busy percent — an *estimate*: probes
    are point samples, so short kernels can slip between them, and on a
    high-RTT tunneled device the link jitter widens the baseline band
    (busy_factor is deliberately 2x).
    """

    def __init__(self, busy_factor=2.0, baseline_samples=8):
        import time

        import jax

        self.busy_factor = busy_factor
        device = jax.local_devices()[0]
        self.device_id = device.id
        self._x = jax.device_put(np.float32(1.0), device)
        self._fn = jax.jit(lambda x: x + np.float32(1.0))
        float(self._fn(self._x))  # compile outside the baseline
        lats = []
        for _ in range(baseline_samples):
            t0 = time.perf_counter()
            float(self._fn(self._x))
            lats.append(time.perf_counter() - t0)
        # min: the emptiest-queue observation is the best idle estimate
        self.baseline_s = max(min(lats), 1e-6)

    def sample(self):
        """One probe: (queue_delay_us, busy 0/1)."""
        import time

        t0 = time.perf_counter()
        float(self._fn(self._x))
        lat = time.perf_counter() - t0
        delay_us = max(0.0, (lat - self.baseline_s) * 1e6)
        busy = 1.0 if lat > self.busy_factor * self.baseline_s else 0.0
        return delay_us, busy


@witness_shared("_lock")
class MetricsManager:
    def __init__(self, metrics_url, interval_s=1.0, timeout_s=5.0,
                 include_local_devices=False, utilization_probe=None):
        self.metrics_url = metrics_url
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.include_local_devices = include_local_devices
        self.utilization_probe = utilization_probe
        self._snapshots = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self.scrape_errors = 0

    def scrape(self):
        try:
            with urllib.request.urlopen(
                self.metrics_url, timeout=self.timeout_s
            ) as r:
                snap = parse_prometheus(
                    r.read().decode("utf-8", errors="replace")
                )
        except Exception:
            # A server with no /metrics endpoint at all is the PRIMARY
            # local-telemetry use case: the local snapshot and the
            # utilization probe must still flow.  (On re-raise the polling
            # loop counts the scrape error; the fallback success path
            # counts it here — exactly once either way.)
            if not self.include_local_devices and self.utilization_probe is None:
                raise
            local = dict(
                self._local_snapshot() if self.include_local_devices else {}
            )
            self._probe_into(local)
            if not local:
                raise
            with self._lock:  # scrape() runs caller- and loop-side
                self.scrape_errors += 1
            return local
        if self.include_local_devices:
            for name, entries in self._local_snapshot().items():
                # server-reported gauges win; local fills the blind spot
                if name not in snap:
                    snap[name] = entries
        self._probe_into(snap)
        return snap

    def _probe_into(self, snap):
        if self.utilization_probe is None:
            return
        try:
            delay_us, busy = self.utilization_probe.sample()
        except Exception:
            return
        labels = (
            f'{{device="{escape_label(self.utilization_probe.device_id)}"'
            ',source="probe"}'
        )
        snap["ctpu_probe_queue_delay_us"] = [(labels, delay_us)]
        snap["ctpu_probe_busy"] = [(labels, busy)]

    _local_snapshot = staticmethod(local_device_snapshot)

    def start(self):
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    snap = self.scrape()
                    with self._lock:
                        self._snapshots.append(snap)
                except Exception:
                    with self._lock:
                        self.scrape_errors += 1
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def swap_snapshots(self):
        """Collect-and-clear, like the managers' timestamp swap."""
        with self._lock:
            snaps = self._snapshots
            self._snapshots = []
        return snaps

    # Series families summarize() folds in wholesale: the LM engine
    # (PR 9-10), the fleet tier (PR 11-12) and the SLO watchdog all
    # export under these prefixes, and a fixed gauge list would silently
    # drop every series added after it was written (which is exactly
    # what happened to ctpu_lm_*/ctpu_fleet_* until this audit).
    SERIES_PREFIXES = ("ctpu_lm_", "ctpu_fleet_", "ctpu_slo_",
                      "ctpu_flight_", "ctpu_prof_")

    @staticmethod
    def summarize(snapshots, gauges=("ctpu_tpu_memory_used_bytes",
                                     "ctpu_tpu_memory_total_bytes",
                                     "ctpu_tpu_memory_peak_bytes",
                                     "ctpu_probe_queue_delay_us"),
                  prefixes=None):
        """Max/avg per gauge over the window's snapshots (the reference
        merges per-GPU utilization/memory the same way), plus every
        series matching :data:`SERIES_PREFIXES`: gauges aggregate as
        avg/max of their per-snapshot label-summed values, ``*_total``
        counters as the window delta (reported as avg==max so the
        report's column pair renders them unchanged)."""
        summary = {}
        for gauge in gauges:
            values = []
            for snap in snapshots:
                for _, v in snap.get(gauge, []):
                    values.append(v)
            if values:
                summary[gauge] = {
                    "avg": float(np.mean(values)),
                    "max": float(np.max(values)),
                }
        prefixes = (
            MetricsManager.SERIES_PREFIXES if prefixes is None else prefixes
        )
        names = sorted({
            name
            for snap in snapshots
            for name in snap
            if name.startswith(tuple(prefixes)) and name not in summary
        })
        for name in names:
            # quantile/rate gauges are NOT additive across label sets:
            # summing two models' p99s reports a latency nobody saw (and
            # summed error rates exceed 1.0) — take the worst label
            # instead; usage/count gauges fold by sum as before
            additive = not (
                name.endswith(("_ms", "_rate", "_pct"))
            )
            fold = sum if additive else max
            sums = [
                fold(v for _, v in snap[name])
                for snap in snapshots
                if snap.get(name)
            ]
            if not sums:
                continue
            if name.endswith("_total"):
                delta = float(sums[-1] - sums[0]) if len(sums) > 1 else float(
                    sums[-1]
                )
                summary[name] = {"avg": delta, "max": delta}
            else:
                summary[name] = {
                    "avg": float(np.mean(sums)),
                    "max": float(np.max(sums)),
                }
        # utilization gauges are emitted in PERCENT: the report renders
        # tpu_metrics with :.0f, which would flatten a 0-1 fraction to 0/1
        util = MetricsManager.utilization(snapshots)
        if util is not None:
            summary["ctpu_server_utilization_pct"] = {
                "avg": util * 100.0, "max": util * 100.0,
            }
        # probe-based estimate: fraction of window probes that found the
        # device busy — utilization without trusting the server under test
        busy = [
            v for snap in snapshots for _, v in snap.get("ctpu_probe_busy", [])
        ]
        if busy:
            summary["ctpu_probe_utilization_pct"] = {
                "avg": float(np.mean(busy)) * 100.0,
                "max": float(np.max(busy)) * 100.0,
            }
        summary.update(MetricsManager.server_breakdown(snapshots))
        return summary

    @staticmethod
    def server_breakdown(snapshots):
        """Server-side per-inference phase breakdown over the window.

        Deltas the cumulative ``ctpu_inference_{queue,compute_*}_duration_us``
        counters (summed across models) between the window's first and last
        scrape and divides by the successful-request delta — so the perf
        report shows where server time went (queue vs compute) next to the
        client-observed latency, the reference perf_analyzer's
        server-side-breakdown column set."""

        def total(snap, name):
            return sum(v for _, v in snap.get(name, []))

        if len(snapshots) < 2:
            return {}
        first, last = snapshots[0], snapshots[-1]
        d_requests = total(last, "ctpu_inference_request_success") - total(
            first, "ctpu_inference_request_success"
        )
        if d_requests <= 0:
            return {}
        out = {}
        for phase in ("queue", "compute_input", "compute_infer",
                      "compute_output"):
            metric = f"ctpu_inference_{phase}_duration_us"
            if metric not in last:
                continue
            avg = (total(last, metric) - total(first, metric)) / d_requests
            # real max: worst per-infer rate over consecutive scrape
            # intervals (reporting max==avg would hide window spikes)
            worst = avg
            for a, b in zip(snapshots, snapshots[1:]):
                d_req = total(b, "ctpu_inference_request_success") - total(
                    a, "ctpu_inference_request_success"
                )
                if d_req <= 0:
                    continue
                rate = (total(b, metric) - total(a, metric)) / d_req
                worst = max(worst, rate)
            out[f"ctpu_server_{phase}_us_per_infer"] = {
                "avg": avg, "max": worst,
            }
        return out

    @staticmethod
    def utilization(snapshots):
        """Server duty cycle over the window: delta(busy_ns) / delta(wall),
        from the ctpu_server_busy_ns counter + scrape timestamps.  The
        nv_gpu_utilization analog; None when fewer than two usable scrapes."""

        def point(snap):
            busy = snap.get("ctpu_server_busy_ns")
            ts = snap.get("ctpu_scrape_timestamp_seconds")
            if not busy or not ts:
                return None
            return ts[0][1], busy[0][1]

        points = [p for p in (point(s) for s in snapshots) if p is not None]
        if len(points) < 2:
            return None
        (t0, b0), (t1, b1) = points[0], points[-1]
        if t1 <= t0:
            return None
        return max(0.0, min(1.0, (b1 - b0) / 1e9 / (t1 - t0)))
