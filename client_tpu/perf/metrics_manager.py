"""Server metrics scraping during measurement.

Parity with the reference MetricsManager (reference
src/c++/perf_analyzer/metrics_manager.h:44-91): a background thread polls
the server's Prometheus ``/metrics`` on an interval and keeps per-window
snapshots; the profiler merges them into each load level's summary.  The
counters of interest are the TPU ones this framework's server exposes
(``ctpu_tpu_memory_*``) plus the inference counters — the
``nv_gpu_utilization`` analog set.
"""

import threading
import urllib.request

import numpy as np


def parse_prometheus(text):
    """Prometheus text format -> {metric_name: [(labels_str, value), ...]}."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value_part = line.rsplit(" ", 1)
            value = float(value_part)
        except ValueError:
            continue
        if "{" in name_part:
            name, labels = name_part.split("{", 1)
            labels = "{" + labels
        else:
            name, labels = name_part, ""
        out.setdefault(name, []).append((labels, value))
    return out


def local_device_snapshot():
    """Device gauges read directly from the local PJRT runtime
    (jax.local_devices()[i].memory_stats()) — the telemetry source of last
    resort when the *server* under test exposes no TPU gauges (any
    third-party KServe server; reference metrics_manager.h:44-91 has the
    same blind spot for non-Triton servers).  Only meaningful when the perf
    process is colocated with the chip.  Returns {} off-device."""
    out = {}
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        labels = f'{{device="{d.id}",source="local"}}'
        used = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit"
        )
        peak = stats.get("peak_bytes_in_use")
        if used is not None:
            out.setdefault("ctpu_tpu_memory_used_bytes", []).append(
                (labels, float(used))
            )
        if limit is not None:
            out.setdefault("ctpu_tpu_memory_total_bytes", []).append(
                (labels, float(limit))
            )
        if peak is not None:
            out.setdefault("ctpu_tpu_memory_peak_bytes", []).append(
                (labels, float(peak))
            )
    return out


class MetricsManager:
    def __init__(self, metrics_url, interval_s=1.0, timeout_s=5.0,
                 include_local_devices=False):
        self.metrics_url = metrics_url
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.include_local_devices = include_local_devices
        self._snapshots = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self.scrape_errors = 0

    def scrape(self):
        try:
            with urllib.request.urlopen(
                self.metrics_url, timeout=self.timeout_s
            ) as r:
                snap = parse_prometheus(
                    r.read().decode("utf-8", errors="replace")
                )
        except Exception:
            # A server with no /metrics endpoint at all is the PRIMARY
            # local-devices use case: the local snapshot must still flow.
            # (On re-raise the polling loop counts the scrape error; the
            # fallback success path counts it here — exactly once either way.)
            if not self.include_local_devices:
                raise
            local = self._local_snapshot()
            if not local:
                raise
            self.scrape_errors += 1
            return dict(local)
        if self.include_local_devices:
            for name, entries in self._local_snapshot().items():
                # server-reported gauges win; local fills the blind spot
                if name not in snap:
                    snap[name] = entries
        return snap

    _local_snapshot = staticmethod(local_device_snapshot)

    def start(self):
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    snap = self.scrape()
                    with self._lock:
                        self._snapshots.append(snap)
                except Exception:
                    self.scrape_errors += 1
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def swap_snapshots(self):
        """Collect-and-clear, like the managers' timestamp swap."""
        with self._lock:
            snaps = self._snapshots
            self._snapshots = []
        return snaps

    @staticmethod
    def summarize(snapshots, gauges=("ctpu_tpu_memory_used_bytes",
                                     "ctpu_tpu_memory_total_bytes",
                                     "ctpu_tpu_memory_peak_bytes")):
        """Max/avg per gauge over the window's snapshots (the reference
        merges per-GPU utilization/memory the same way)."""
        summary = {}
        for gauge in gauges:
            values = []
            for snap in snapshots:
                for _, v in snap.get(gauge, []):
                    values.append(v)
            if values:
                summary[gauge] = {
                    "avg": float(np.mean(values)),
                    "max": float(np.max(values)),
                }
        util = MetricsManager.utilization(snapshots)
        if util is not None:
            summary["ctpu_server_utilization"] = {"avg": util, "max": util}
        return summary

    @staticmethod
    def utilization(snapshots):
        """Server duty cycle over the window: delta(busy_ns) / delta(wall),
        from the ctpu_server_busy_ns counter + scrape timestamps.  The
        nv_gpu_utilization analog; None when fewer than two usable scrapes."""

        def point(snap):
            busy = snap.get("ctpu_server_busy_ns")
            ts = snap.get("ctpu_scrape_timestamp_seconds")
            if not busy or not ts:
                return None
            return ts[0][1], busy[0][1]

        points = [p for p in (point(s) for s in snapshots) if p is not None]
        if len(points) < 2:
            return None
        (t0, b0), (t1, b1) = points[0], points[-1]
        if t1 <= t0:
            return None
        return max(0.0, min(1.0, (b1 - b0) / 1e9 / (t1 - t0)))
