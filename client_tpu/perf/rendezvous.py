"""Multi-rank coordination for data-parallel benchmarking.

The reference coordinates multiple perf_analyzer ranks with dlopen'd MPI
(reference mpi_utils.h:32-84: Init/Barrier/Bcast/Finalize) and requires
*all* ranks to reach stability before any stops measuring
(AllMPIRanksAreStable, inference_profiler.h:537).  The TPU-native rebuild
replaces MPI with a tiny TCP rendezvous — the same shape jax.distributed
uses for its coordinator — so N perf processes on one or many hosts can
drive one or many models concurrently:

  rank 0:  python -m client_tpu.perf ... --world-size 2 --rank 0
  rank 1:  python -m client_tpu.perf ... --world-size 2 --rank 1 \
               --rendezvous-addr <rank0-host>:<port>

Operations: ``barrier()`` and ``all_gather(obj)`` (JSON payloads,
length-prefixed frames).  Rank 0 serves; other ranks connect with retry.
"""

import json
import socket
import struct
import time

from client_tpu.resilience import backoff_delays
from client_tpu.utils import InferenceServerException


def send_frame(sock, obj):
    """Write one length-prefixed JSON frame — the transport primitive the
    rendezvous AND the fleet cache tier (serve/fleet.py) share."""
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise InferenceServerException("rendezvous peer disconnected")
        buf += chunk
    return buf


def recv_frame(sock):
    """Read one length-prefixed JSON frame (see :func:`send_frame`)."""
    (length,) = struct.unpack("<I", _recv_exact(sock, 4))
    return json.loads(_recv_exact(sock, length).decode("utf-8"))


# historical private names (pre-fleet callers)
_send_frame = send_frame
_recv_frame = recv_frame


class Rendezvous:
    """Barrier + all-gather across ``world_size`` processes."""

    def __init__(self, rank, world_size, addr="127.0.0.1:29400",
                 connect_timeout_s=60.0):
        if not (0 <= rank < world_size):
            raise InferenceServerException(
                f"rank {rank} out of range for world size {world_size}"
            )
        self.rank = rank
        self.world_size = world_size
        host, _, port = addr.rpartition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port)
        self._peers = {}  # rank -> socket (rank 0 only)
        self._server = None
        self._sock = None  # connection to rank 0 (ranks > 0)
        if world_size > 1:
            if rank == 0:
                self._serve(connect_timeout_s)
            else:
                self._connect(connect_timeout_s)

    def _serve(self, timeout_s):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self._host, self._port))
        srv.listen(self.world_size)
        srv.settimeout(timeout_s)
        self._server = srv
        deadline = time.monotonic() + timeout_s
        while len(self._peers) < self.world_size - 1:
            if time.monotonic() > deadline:
                raise InferenceServerException(
                    f"rendezvous timeout: {len(self._peers) + 1}/"
                    f"{self.world_size} ranks joined"
                )
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            # Validate the hello: a duplicate or out-of-range rank would
            # silently evict a legitimate peer (all_gather then hangs or
            # mis-orders); reject the connection instead.  Any handshake
            # failure (garbage bytes, early disconnect, RST on the reject
            # send) only drops THAT connection — a port scanner or crashing
            # peer must not abort the whole rendezvous.
            try:
                hello = _recv_frame(conn)
                peer = hello.get("rank")
                if (
                    not isinstance(peer, int)
                    or isinstance(peer, bool)
                    or not (1 <= peer < self.world_size)
                ):
                    _send_frame(
                        conn,
                        {"error": f"invalid rank {peer!r} for world size "
                                  f"{self.world_size}"},
                    )
                    conn.close()
                    continue
                if peer in self._peers:
                    _send_frame(conn, {"error": f"rank {peer} already joined"})
                    conn.close()
                    continue
                _send_frame(conn, {"ok": True})
            except (InferenceServerException, OSError, ValueError):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            self._peers[peer] = conn

    def _connect(self, timeout_s):
        deadline = time.monotonic() + timeout_s
        last_err = None
        # Jittered exponential backoff between attempts: rank 0 binding
        # late is normal, but hammering ECONNREFUSED in a tight loop burns
        # a core per waiting rank, and N ranks retrying in lockstep arrive
        # as a thundering herd the moment the port opens.
        delays = backoff_delays(initial_s=0.05, multiplier=2.0, max_s=1.0)
        while time.monotonic() < deadline:
            try:
                sock = socket.create_connection(
                    (self._host, self._port), timeout=5.0
                )
                sock.settimeout(timeout_s)
                _send_frame(sock, {"rank": self.rank})
                ack = _recv_frame(sock)
                if "error" in ack:
                    sock.close()
                    raise InferenceServerException(
                        f"rendezvous rejected rank {self.rank}: {ack['error']}"
                    )
                self._sock = sock
                return
            except OSError as e:
                last_err = e
                time.sleep(
                    min(next(delays), max(deadline - time.monotonic(), 0.0))
                )
        raise InferenceServerException(
            f"unable to reach rendezvous at {self._host}:{self._port}: "
            f"{last_err}"
        )

    def all_gather(self, obj):
        """Every rank contributes ``obj``; all receive the rank-ordered list."""
        if self.world_size == 1:
            return [obj]
        if self.rank == 0:
            gathered = {0: obj}
            for rank, sock in self._peers.items():
                gathered[rank] = _recv_frame(sock)["payload"]
            result = [gathered[r] for r in range(self.world_size)]
            for sock in self._peers.values():
                _send_frame(sock, {"payload": result})
            return result
        _send_frame(self._sock, {"payload": obj})
        return _recv_frame(self._sock)["payload"]

    def barrier(self):
        self.all_gather(None)

    def all_ranks_stable(self, local_stable):
        """AllMPIRanksAreStable analog: true only when every rank is."""
        return all(self.all_gather(bool(local_stable)))

    def close(self):
        for sock in self._peers.values():
            sock.close()
        self._peers = {}
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        if self._server is not None:
            self._server.close()
            self._server = None
