"""In-process fake TorchServe / TensorFlow-Serving endpoints.

The perf harness's pluggable-backend layer (client_backend.py) promises that
the load engine works over non-KServe protocol families, the way the
reference ships TorchServe and TF-Serving client backends (reference
src/c++/perf_analyzer/client_backend/torchserve/torchserve_http_client.cc,
tensorflow_serving/tfserve_grpc_client.cc).  These stdlib-only fakes give
the harness (and its tests) hermetic servers speaking each service's actual
REST dialect:

- TorchServe inference API: ``GET /ping``, ``POST /predictions/{model}``
  (opaque request body -> JSON prediction).
- TF-Serving REST API: ``GET /v1/models/{m}``, ``GET /v1/models/{m}/metadata``,
  ``POST /v1/models/{m}:predict`` ({"instances": ...} -> {"predictions": ...}).

Both run a deterministic model (sum over the payload) so client-side
validation has ground truth.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np


class _Quiet(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # no stderr chatter under load
        pass

    def _reply(self, code, payload, content_type="application/json"):
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b""


class _TorchServeHandler(_Quiet):
    """TorchServe inference-API surface (the subset the reference backend
    drives: ping + predictions; plus the management models listing)."""

    def do_GET(self):
        if self.path == "/ping":
            self._reply(200, {"status": "Healthy"})
        elif self.path.startswith("/models"):
            name = self.path.rsplit("/", 1)[-1]
            if name in self.server.models or name == "models":
                self._reply(
                    200,
                    [{"modelName": m, "modelVersion": "1.0"}
                     for m in self.server.models],
                )
            else:
                self._reply(404, {"code": 404, "message": f"Model not found: {name}"})
        else:
            self._reply(404, {"code": 404, "message": "unknown path"})

    def do_POST(self):
        if not self.path.startswith("/predictions/"):
            return self._reply(404, {"code": 404, "message": "unknown path"})
        name = self.path[len("/predictions/"):].split("/")[0]
        if name not in self.server.models:
            return self._reply(
                404, {"code": 404, "message": f"Model not found: {name}"}
            )
        raw = self._body()
        with self.server.stats_lock:
            self.server.request_count += 1
        # deterministic "model": sum of payload interpreted as f32 when
        # aligned, else byte sum — clients can validate either way
        if len(raw) % 4 == 0 and raw:
            value = float(np.frombuffer(raw, np.float32).sum())
        else:
            value = float(np.frombuffer(raw, np.uint8).sum())
        self._reply(200, [round(value, 4)])


class _TfServingHandler(_Quiet):
    """TF-Serving REST predict surface."""

    def do_GET(self):
        parts = self.path.strip("/").split("/")
        if len(parts) >= 2 and parts[0] == "v1" and parts[1] == "models":
            name = parts[2].split(":")[0] if len(parts) > 2 else ""
            if name not in self.server.models:
                return self._reply(
                    404, {"error": f"Model {name} not found"}
                )
            if len(parts) > 3 and parts[3] == "metadata":
                return self._reply(200, {
                    "model_spec": {"name": name, "version": "1"},
                    "metadata": {"signature_def": {"signature_def": {
                        "serving_default": {
                            "inputs": {"input": {"dtype": "DT_FLOAT"}},
                            "outputs": {"output": {"dtype": "DT_FLOAT"}},
                        }}}},
                })
            return self._reply(200, {"model_version_status": [
                {"version": "1", "state": "AVAILABLE",
                 "status": {"error_code": "OK", "error_message": ""}}]})
        self._reply(404, {"error": "unknown path"})

    def do_POST(self):
        parts = self.path.strip("/").split("/")
        if (len(parts) != 3 or parts[0] != "v1" or parts[1] != "models"
                or not parts[2].endswith(":predict")):
            return self._reply(404, {"error": "unknown path"})
        name = parts[2][: -len(":predict")]
        if name not in self.server.models:
            return self._reply(404, {"error": f"Model {name} not found"})
        try:
            doc = json.loads(self._body())
            instances = doc["instances"]
        except Exception:
            return self._reply(400, {"error": "malformed predict request"})
        with self.server.stats_lock:
            self.server.request_count += 1
        predictions = [
            [float(np.asarray(inst, dtype=np.float64).sum())]
            for inst in instances
        ]
        self._reply(200, {"predictions": predictions})


class _FakeService:
    def __init__(self, handler, models):
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self.httpd.models = set(models)
        self.httpd.stats_lock = threading.Lock()
        self.httpd.request_count = 0
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def start(self):
        self._thread.start()
        return self

    @property
    def url(self):
        host, port = self.httpd.server_address[:2]
        return f"{host}:{port}"

    @property
    def request_count(self):
        with self.httpd.stats_lock:
            return self.httpd.request_count

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def fake_torchserve(models=("resnet",)):
    return _FakeService(_TorchServeHandler, models)


def fake_tfserving(models=("half_plus_two",)):
    return _FakeService(_TfServingHandler, models)


class _FakeTfServingGrpc:
    """Hermetic gRPC PredictionService (the real protocol surface the
    TFSERVE backend speaks): Predict sums each row of the first input into
    an ``output`` DT_FLOAT tensor; GetModelStatus reports AVAILABLE."""

    def __init__(self, models):
        self.models = set(models)
        self.request_count = 0
        self.stats_lock = threading.Lock()
        self._server = None
        self._port = 0

    def start(self):
        from concurrent import futures

        import grpc

        from client_tpu._proto import tfserve_pb2 as tfs

        outer = self

        def Predict(request, context):
            if request.model_spec.name not in outer.models:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"Servable not found: {request.model_spec.name}",
                )
            with outer.stats_lock:
                outer.request_count += 1
            response = tfs.PredictResponse()
            response.model_spec.name = request.model_spec.name
            out = response.outputs["output"]
            out.dtype = tfs.DT_FLOAT
            for name, tensor in sorted(request.inputs.items()):
                shape = [d.size for d in tensor.tensor_shape.dim]
                if tensor.tensor_content:
                    arr = np.frombuffer(
                        tensor.tensor_content, dtype=np.float32
                    )
                elif tensor.float_val:
                    arr = np.asarray(list(tensor.float_val), np.float32)
                else:
                    arr = np.zeros(0, np.float32)
                rows = int(shape[0]) if shape else 1
                sums = arr.reshape(rows, -1).sum(axis=1) if arr.size else (
                    np.zeros(rows, np.float32)
                )
                out.tensor_content = np.asarray(
                    sums, np.float32
                ).tobytes()
                out.tensor_shape.dim.add().size = rows
                out.tensor_shape.dim.add().size = 1
                break  # first input only (half_plus_two-style single-input)
            return response

        def GetModelStatus(request, context):
            response = tfs.GetModelStatusResponse()
            if request.model_spec.name in outer.models:
                s = response.model_version_status.add()
                s.version = 1
                s.state = tfs.ModelVersionStatus.AVAILABLE
            return response

        def GetModelMetadata(request, context):
            response = tfs.GetModelMetadataResponse()
            response.model_spec.name = request.model_spec.name
            response.model_spec.version.value = 1
            return response

        handlers = {
            "Predict": grpc.unary_unary_rpc_method_handler(
                Predict,
                request_deserializer=tfs.PredictRequest.FromString,
                response_serializer=tfs.PredictResponse.SerializeToString,
            ),
            "GetModelMetadata": grpc.unary_unary_rpc_method_handler(
                GetModelMetadata,
                request_deserializer=(
                    tfs.GetModelMetadataRequest.FromString
                ),
                response_serializer=(
                    tfs.GetModelMetadataResponse.SerializeToString
                ),
            ),
        }
        model_handlers = {
            "GetModelStatus": grpc.unary_unary_rpc_method_handler(
                GetModelStatus,
                request_deserializer=tfs.GetModelStatusRequest.FromString,
                response_serializer=(
                    tfs.GetModelStatusResponse.SerializeToString
                ),
            ),
        }
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                "tensorflow.serving.PredictionService", handlers
            ),
            grpc.method_handlers_generic_handler(
                "tensorflow.serving.ModelService", model_handlers
            ),
        ))
        self._port = self._server.add_insecure_port("127.0.0.1:0")
        self._server.start()
        return self

    @property
    def url(self):
        return f"127.0.0.1:{self._port}"

    def stop(self):
        if self._server is not None:
            self._server.stop(grace=1)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def fake_tfserving_grpc(models=("half_plus_two",)):
    return _FakeTfServingGrpc(models)
