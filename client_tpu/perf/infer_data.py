"""Materialize per-request InferInput/InferRequestedOutput objects.

Parity with the reference's InferDataManager family (reference
src/c++/perf_analyzer/iinfer_data_manager.h:39-60 and
infer_data_manager{,_base,_shm,_factory}): a factory picks the plain variant
(tensor bytes inline in each request) or a shared-memory variant that
pre-stages input data in system or TPU regions and hands out
region-referencing inputs.  The TPU variant is the HBM-resident path — data
is device_put once at init and requests carry only region references.
"""

import numpy as np

from client_tpu.utils import InferenceServerException, serialized_byte_size


class SharedMemoryType:
    NONE = "none"
    SYSTEM = "system"
    TPU = "tpu"


class InferData:
    """Prepared request objects for one (stream, step)."""

    def __init__(self, inputs, outputs):
        self.inputs = inputs
        self.outputs = outputs


def _nbytes(arr):
    if arr.dtype == np.object_:
        return serialized_byte_size(arr)
    return arr.nbytes


class InferDataManager:
    """Plain variant: every request carries tensor bytes.

    Request objects are built once per (stream, step) at init and reused for
    every send (the reference prepares infer data per context and rotates;
    per-request re-serialization would inflate measured client latency).
    The cached objects are treated as immutable by the workers.
    """

    def __init__(self, backend, data_loader, inputs_metadata, outputs_metadata):
        self._backend = backend
        self._loader = data_loader
        self._inputs_meta = inputs_metadata
        self._outputs_meta = outputs_metadata
        self._cache = {}

    def init(self):
        for s in range(self._loader.num_streams):
            for t in range(self._loader.num_steps(s)):
                self._cache[(s, t)] = self._build(s, t)

    def _build(self, stream_id, step_id):
        step = self._loader.get_input_data(stream_id, step_id)
        InferInput = self._backend.infer_input_cls
        Requested = self._backend.requested_output_cls
        inputs = []
        for meta in self._inputs_meta:
            name = meta["name"]
            td = step.get(name)
            if td is None:
                continue  # optional input absent from this step
            inp = InferInput(name, list(td.array.shape), meta["datatype"])
            inp.set_data_from_numpy(td.array)
            inputs.append(inp)
        outputs = [Requested(m["name"]) for m in self._outputs_meta]
        return InferData(inputs, outputs)

    def get_infer_data(self, stream_id, step_id):
        return self._cache[(stream_id, step_id)]

    def cleanup(self):
        pass


class _ShmInferDataManagerBase(InferDataManager):
    """Pre-stages every (stream, step) tensor into regions at init; requests
    reference regions by name+offset (infer_data_manager_shm.h analog)."""

    region_prefix = "perf_shm"

    def __init__(self, backend, data_loader, inputs_metadata, outputs_metadata,
                 output_byte_size=0):
        super().__init__(backend, data_loader, inputs_metadata, outputs_metadata)
        self._regions = {}  # (stream, step, name) -> (region_name, nbytes)
        self._out_regions = {}  # name -> (region_name, byte_size)
        self._output_byte_size = output_byte_size

    def _create_and_register(self, region_name, arrays, total):
        raise NotImplementedError

    def _create_output_region(self, region_name, byte_size):
        raise NotImplementedError

    def init(self):
        for s, steps in enumerate(self._loader.streams):
            for t, step in enumerate(steps):
                for name, td in step.items():
                    region_name = f"{self.region_prefix}_{s}_{t}_{name}"
                    nbytes = _nbytes(td.array)
                    self._create_and_register(region_name, [td.array], nbytes)
                    self._regions[(s, t, name)] = (region_name, nbytes)
        if self._output_byte_size:
            for meta in self._outputs_meta:
                region_name = f"{self.region_prefix}_out_{meta['name']}"
                self._create_output_region(region_name, self._output_byte_size)
                self._out_regions[meta["name"]] = (
                    region_name, self._output_byte_size
                )
        for s in range(self._loader.num_streams):
            for t in range(self._loader.num_steps(s)):
                self._cache[(s, t)] = self._build(s, t)

    def _build(self, stream_id, step_id):
        step = self._loader.get_input_data(stream_id, step_id)
        InferInput = self._backend.infer_input_cls
        Requested = self._backend.requested_output_cls
        inputs = []
        for meta in self._inputs_meta:
            name = meta["name"]
            td = step.get(name)
            if td is None:
                continue
            region_name, nbytes = self._regions[(stream_id, step_id, name)]
            inp = InferInput(name, list(td.array.shape), meta["datatype"])
            inp.set_shared_memory(region_name, nbytes)
            inputs.append(inp)
        outputs = []
        for meta in self._outputs_meta:
            out = Requested(meta["name"])
            if meta["name"] in self._out_regions:
                region_name, byte_size = self._out_regions[meta["name"]]
                out.set_shared_memory(region_name, byte_size)
            outputs.append(out)
        return InferData(inputs, outputs)


class SystemShmInferDataManager(_ShmInferDataManagerBase):
    region_prefix = "perf_sysshm"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._handles = []

    def _create_and_register(self, region_name, arrays, total):
        from client_tpu.utils import shared_memory as sysshm

        key = "/" + region_name
        h = sysshm.create_shared_memory_region(region_name, key, total)
        sysshm.set_shared_memory_region(h, arrays)
        self._backend.register_system_shared_memory(region_name, key, total)
        self._handles.append(h)

    def _create_output_region(self, region_name, byte_size):
        from client_tpu.utils import shared_memory as sysshm

        key = "/" + region_name
        h = sysshm.create_shared_memory_region(region_name, key, byte_size)
        self._backend.register_system_shared_memory(region_name, key, byte_size)
        self._handles.append(h)

    def cleanup(self):
        from client_tpu.utils import shared_memory as sysshm

        try:
            self._backend.unregister_shared_memory()
        except InferenceServerException:
            pass
        for h in self._handles:
            try:
                sysshm.destroy_shared_memory_region(h)
            except InferenceServerException:
                pass
        self._handles = []


class TpuShmInferDataManager(_ShmInferDataManagerBase):
    """HBM-resident input staging over client_tpu.utils.tpu_shared_memory."""

    region_prefix = "perf_tpushm"

    def __init__(self, *args, device_id=0, completion_sync=False, **kwargs):
        super().__init__(*args, **kwargs)
        self._device_id = device_id
        self.completion_sync = completion_sync
        self._handles = []
        self._out_handles = []

    def _make_region(self, region_name, byte_size):
        from client_tpu.utils import tpu_shared_memory as tpushm

        h = tpushm.create_shared_memory_region(
            region_name, byte_size, self._device_id
        )
        self._handles.append(h)
        return h

    def sync_outputs(self):
        """Force a D2H read of every output region so the request latency
        covers completion, not dispatch ack (--tpu-shm-sync)."""
        for h, byte_size in self._out_handles:
            h.read(0, byte_size)

    def _create_and_register(self, region_name, arrays, total):
        from client_tpu.utils import tpu_shared_memory as tpushm

        h = self._make_region(region_name, total)
        tpushm.set_shared_memory_region(h, arrays)
        self._backend.register_tpu_shared_memory(
            region_name, tpushm.get_raw_handle(h), self._device_id, total
        )

    def _create_output_region(self, region_name, byte_size):
        from client_tpu.utils import tpu_shared_memory as tpushm

        h = self._make_region(region_name, byte_size)
        self._out_handles.append((h, byte_size))
        self._backend.register_tpu_shared_memory(
            region_name, tpushm.get_raw_handle(h), self._device_id, byte_size
        )

    def cleanup(self):
        from client_tpu.utils import tpu_shared_memory as tpushm

        try:
            self._backend.unregister_shared_memory()
        except InferenceServerException:
            pass
        for h in self._handles:
            try:
                tpushm.destroy_shared_memory_region(h)
            except InferenceServerException:
                pass
        self._handles = []
        self._out_handles = []


def create_infer_data_manager(backend, data_loader, inputs_meta, outputs_meta,
                              shared_memory=SharedMemoryType.NONE,
                              output_shm_byte_size=0, device_id=0,
                              tpu_completion_sync=False):
    """Factory (infer_data_manager_factory.h analog).  ``tpu_completion_sync``
    makes each request latency cover output completion (forced D2H) rather
    than dispatch ack.  Every TPU region carries a native host window, so
    out-of-process servers always attach (no staging toggle needed)."""
    if shared_memory == SharedMemoryType.NONE:
        return InferDataManager(backend, data_loader, inputs_meta, outputs_meta)
    if shared_memory == SharedMemoryType.SYSTEM:
        return SystemShmInferDataManager(
            backend, data_loader, inputs_meta, outputs_meta,
            output_byte_size=output_shm_byte_size,
        )
    if shared_memory == SharedMemoryType.TPU:
        return TpuShmInferDataManager(
            backend, data_loader, inputs_meta, outputs_meta,
            output_byte_size=output_shm_byte_size, device_id=device_id,
            completion_sync=tpu_completion_sync,
        )
    raise InferenceServerException(
        f"unknown shared memory type '{shared_memory}'"
    )
