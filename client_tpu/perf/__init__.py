"""perf — the framework's perf_analyzer-class load & measurement harness.

TPU-native rebuild of the reference perf_analyzer (reference
src/c++/perf_analyzer/, SURVEY.md §2.3): pluggable client backends
(gRPC/HTTP/in-process/mock), data loading (generated / directory / JSON),
shared-memory input staging (system or TPU HBM), concurrency and
request-rate load managers with Poisson/constant/custom schedules, stateful
sequence workloads, a windowed stability-seeking profiler, and stdout/CSV
reporting.  CLI: ``python -m client_tpu.perf``.
"""

from client_tpu.perf.client_backend import (
    BackendKind,
    ClientBackend,
    ClientBackendFactory,
    MockClientBackend,
    MockStats,
)
from client_tpu.perf.data_loader import DataLoader
from client_tpu.perf.infer_data import (
    SharedMemoryType,
    create_infer_data_manager,
)
from client_tpu.perf.load_manager import (
    ConcurrencyManager,
    CustomLoadManager,
    LoadManager,
    RequestRateManager,
)
from client_tpu.perf.model_parser import ModelParser, SchedulerType
from client_tpu.perf.profiler import InferenceProfiler, PerfStatus
from client_tpu.perf.report import print_summary, write_csv, write_json
from client_tpu.perf.sequence_manager import SequenceManager

__all__ = [
    "BackendKind",
    "ClientBackend",
    "ClientBackendFactory",
    "ConcurrencyManager",
    "CustomLoadManager",
    "DataLoader",
    "InferenceProfiler",
    "LoadManager",
    "MockClientBackend",
    "MockStats",
    "PerfStatus",
    "RequestRateManager",
    "SequenceManager",
    "SharedMemoryType",
    "create_infer_data_manager",
    "print_summary",
    "write_csv",
    "write_json",
]
