"""Input-data provisioning for the perf harness.

Covers the reference DataLoader's three sources (reference
src/c++/perf_analyzer/data_loader.h:38-146): generated (random/zeros),
a directory of raw tensor files, and the multi-stream multi-step JSON format
(``{"data": [...]}`` with typed or b64 content), plus expected-output
validation data.
"""

import base64
import json
import os

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    serialize_byte_tensor,
    triton_to_np_dtype,
)


def _resolve_shape(dims, batch_size, shape_overrides, name):
    shape = list(shape_overrides.get(name, dims))
    out = []
    for i, d in enumerate(shape):
        if d in (-1, "-1"):
            if i == 0 and batch_size:
                out.append(int(batch_size))  # dynamic batch dim
                continue
            raise InferenceServerException(
                f"input '{name}' has dynamic shape {shape}; provide --shape "
                f"{name}:d1,d2,..."
            )
        out.append(int(d))
    return out


class TensorData:
    """One concrete tensor payload for a (stream, step)."""

    def __init__(self, array, is_shape_tensor=False):
        self.array = array
        self.is_shape_tensor = is_shape_tensor


class DataLoader:
    """Produces per-(stream, step) input tensors and expected outputs.

    ``streams`` is a list of steps; each step maps input name -> TensorData.
    Sequence workloads walk the steps of one stream in order; stateless
    workloads round-robin over (stream, step).
    """

    def __init__(self, inputs_metadata, batch_size=1, shape_overrides=None,
                 rng_seed=0):
        self._inputs = inputs_metadata  # list of {name, datatype, shape}
        self._batch = batch_size
        self._shapes = shape_overrides or {}
        self._rng = np.random.default_rng(rng_seed)
        self.streams = []
        self.expected_outputs = []  # parallel to streams: step -> {name: array}

    # -- generation ----------------------------------------------------------

    def generate_data(self, zero_data=False, string_length=16, num_steps=1):
        """Random (or zero) data, one stream (data_loader.h GenerateData)."""
        steps = []
        for _ in range(num_steps):
            step = {}
            for meta in self._inputs:
                name = meta["name"]
                shape = _resolve_shape(
                    meta["shape"], self._batch, self._shapes, name
                )
                step[name] = TensorData(
                    self._gen_tensor(meta["datatype"], shape, zero_data,
                                     string_length)
                )
            steps.append(step)
        self.streams = [steps]
        self.expected_outputs = [[{} for _ in steps]]

    def _gen_tensor(self, datatype, shape, zero, string_length):
        if datatype == "BYTES":
            if zero:
                flat = [b"" for _ in range(int(np.prod(shape)))]
            else:
                alphabet = np.frombuffer(
                    b"abcdefghijklmnopqrstuvwxyz0123456789", np.uint8
                )
                flat = [
                    bytes(self._rng.choice(alphabet, string_length))
                    for _ in range(int(np.prod(shape)))
                ]
            return np.array(flat, dtype=np.object_).reshape(shape)
        np_dtype = triton_to_np_dtype(datatype)
        if np_dtype is None:
            raise InferenceServerException(f"unsupported datatype {datatype}")
        if zero:
            return np.zeros(shape, np_dtype)
        if np.issubdtype(np_dtype, np.floating):
            return self._rng.random(shape).astype(np_dtype)
        if np_dtype == np.bool_:
            return self._rng.integers(0, 2, shape).astype(np.bool_)
        info = np.iinfo(np_dtype)
        lo, hi = max(info.min, -1024), min(info.max, 1024)
        return self._rng.integers(lo, hi + 1, shape).astype(np_dtype)

    def generate_prefix_share(self, share, num_prompts=16, shared_pool=4,
                              scalar_int_value=16, vocab=256):
        """LM workload with a controlled prompt-prefix share (the
        ``--prefix-share`` knob): ``num_prompts`` streams whose token
        input starts with one of ``shared_pool`` shared prefixes covering
        ``share`` of the prompt, the tail unique per stream — so a
        KV prefix cache's prefill savings are measurable from the CLI
        (share 0.8 ≈ 80% of prefill compute adoptable once warm).

        The prompt rides the first multi-element INT tensor (``TOKENS``
        by name when present); values stay in ``[1, vocab)`` so byte-
        vocab LMs accept them.  Single-element INT inputs (``MAX_TOKENS``
        and friends) get ``scalar_int_value`` — a random budget could be
        negative, which would make every stream empty.  Other inputs
        generate as usual.
        """
        share = float(share)
        if not 0.0 <= share <= 1.0:
            raise InferenceServerException(
                f"--prefix-share must be in [0, 1], got {share}"
            )
        token_meta = None
        for meta in self._inputs:
            shape = _resolve_shape(
                meta["shape"], self._batch, self._shapes, meta["name"]
            )
            if not meta["datatype"].startswith(("INT", "UINT")):
                continue
            if meta["name"] == "TOKENS":
                token_meta = meta
                break
            if token_meta is None and int(np.prod(shape)) > 1:
                token_meta = meta
        if token_meta is None:
            raise InferenceServerException(
                "--prefix-share needs an integer token input (e.g. the "
                "LM models' TOKENS); this model has none"
            )
        token_shape = _resolve_shape(
            token_meta["shape"], self._batch, self._shapes,
            token_meta["name"],
        )
        prompt_len = int(np.prod(token_shape))
        prefix_len = int(round(share * prompt_len))
        prefixes = [
            self._rng.integers(1, vocab, prefix_len).astype(np.int32)
            for _ in range(max(int(shared_pool), 1))
        ]
        self.streams = []
        for i in range(int(num_prompts)):
            step = {}
            for meta in self._inputs:
                name = meta["name"]
                shape = _resolve_shape(
                    meta["shape"], self._batch, self._shapes, name
                )
                if meta is token_meta:
                    row = self._rng.integers(
                        1, vocab, prompt_len
                    ).astype(np.int32)
                    row[:prefix_len] = prefixes[i % len(prefixes)]
                    arr = row.reshape(token_shape)
                elif (meta["datatype"].startswith(("INT", "UINT"))
                        and int(np.prod(shape)) == 1):
                    arr = np.full(shape, int(scalar_int_value),
                                  triton_to_np_dtype(meta["datatype"]))
                else:
                    arr = self._gen_tensor(meta["datatype"], shape, False, 16)
                step[name] = TensorData(arr)
            self.streams.append([step])
        self.expected_outputs = [[{}] for _ in self.streams]

    # -- directory of raw files ----------------------------------------------

    def read_data_from_dir(self, data_dir):
        """One file per input, raw little-endian bytes (ReadDataFromDir)."""
        step = {}
        for meta in self._inputs:
            name = meta["name"]
            path = os.path.join(data_dir, name)
            if not os.path.exists(path):
                raise InferenceServerException(
                    f"missing input data file {path}"
                )
            shape = _resolve_shape(meta["shape"], self._batch, self._shapes, name)
            with open(path, "rb") as f:
                raw = f.read()
            if meta["datatype"] == "BYTES":
                from client_tpu.utils import deserialize_bytes_tensor

                arr = deserialize_bytes_tensor(
                    np.frombuffer(raw, np.uint8)
                ).reshape(shape)
            else:
                np_dtype = triton_to_np_dtype(meta["datatype"])
                arr = np.frombuffer(raw, np_dtype).reshape(shape)
            step[name] = TensorData(arr)
        self.streams = [[step]]
        self.expected_outputs = [[{}]]

    # -- JSON ----------------------------------------------------------------

    def read_data_from_json(self, path_or_obj):
        """The reference's JSON format (ReadDataFromJSON): ``data`` is a list
        of streams; each stream is a list of steps (or a single step dict);
        values may be flat typed lists, ``{"content": [...], "shape": [...]}``
        dicts, or ``{"b64": "..."}``; ``validation_data`` mirrors it for
        expected outputs."""
        if isinstance(path_or_obj, (str, os.PathLike)):
            with open(path_or_obj) as f:
                doc = json.load(f)
        else:
            doc = path_or_obj
        if "data" not in doc:
            raise InferenceServerException('JSON input data needs a "data" key')
        self.streams = [
            self._parse_stream(stream) for stream in doc["data"]
        ]
        val = doc.get("validation_data")
        if val:
            self.expected_outputs = [
                self._parse_stream(stream, outputs=True) for stream in val
            ]
        else:
            self.expected_outputs = [
                [{} for _ in steps] for steps in self.streams
            ]

    def _parse_stream(self, stream, outputs=False):
        if isinstance(stream, dict):
            stream = [stream]
        steps = []
        for step_doc in stream:
            step = {}
            metas = (
                {m["name"]: m for m in self._inputs} if not outputs else None
            )
            for name, value in step_doc.items():
                meta = metas.get(name) if metas else None
                step[name] = self._parse_tensor(name, value, meta)
            steps.append(step)
        return steps

    def _parse_tensor(self, name, value, meta):
        datatype = meta["datatype"] if meta else None
        shape = None
        content = value
        if isinstance(value, dict):
            if "b64" in value:
                raw = base64.b64decode(value["b64"])
                if meta is None:
                    raise InferenceServerException(
                        f"b64 content for unknown tensor '{name}'"
                    )
                rshape = _resolve_shape(
                    value.get("shape", meta["shape"]), self._batch,
                    self._shapes, name,
                )
                if datatype == "BYTES":
                    from client_tpu.utils import deserialize_bytes_tensor

                    flat = deserialize_bytes_tensor(
                        np.frombuffer(raw, np.uint8)
                    )
                    return TensorData(flat.reshape(rshape))
                np_dtype = triton_to_np_dtype(datatype)
                return TensorData(np.frombuffer(raw, np_dtype).reshape(rshape))
            shape = value.get("shape")
            content = value.get("content")
            if content is None:
                raise InferenceServerException(
                    f"tensor '{name}' dict needs 'content' or 'b64'"
                )
        flat = np.asarray(content).reshape(-1)
        if datatype == "BYTES" or (datatype is None and flat.dtype.kind in "US"):
            arr = np.array(
                [s.encode() if isinstance(s, str) else s for s in flat],
                dtype=np.object_,
            )
        elif datatype is not None:
            arr = flat.astype(triton_to_np_dtype(datatype))
        else:
            arr = flat
        if shape is None and meta is not None:
            shape = _resolve_shape(meta["shape"], self._batch, self._shapes, name)
        if shape is not None:
            arr = arr.reshape(shape)
        return TensorData(arr)

    # -- access --------------------------------------------------------------

    @property
    def num_streams(self):
        return len(self.streams)

    def num_steps(self, stream_id):
        return len(self.streams[stream_id])

    def get_input_data(self, stream_id, step_id):
        return self.streams[stream_id][step_id]

    def get_expected_outputs(self, stream_id, step_id):
        if stream_id < len(self.expected_outputs):
            steps = self.expected_outputs[stream_id]
            if step_id < len(steps):
                return steps[step_id]
        return {}
