"""Pluggable client-backend layer for the perf harness.

TPU-native re-design of the reference's client_backend abstraction
(reference src/c++/perf_analyzer/client_backend/client_backend.h:134-139
BackendKind, :250-307 factory, :335-455 unified API): one interface over the
transport variants so the load engine never touches protocol details.

Backends:
- ``triton_grpc`` / ``triton_http`` — the framework's own KServe-v2 clients
  over the network (any Triton-compatible server).
- ``inprocess`` — the in-process InferenceEngine, no sockets (the analog of
  the reference's TRITON_C_API dlopen backend, but over the engine object
  instead of libtritonserver.so).
- ``mock`` — deterministic fake with injectable latency/error schedules
  (reference mock_client_backend.h:405-583), used by the unit tests.
"""

import json
import threading
import time

import numpy as np

from client_tpu.serve.prof import PhaseProfiler
from client_tpu.utils import InferenceServerException

# Client-side wire accounting (serve/prof.py): every backend commits a
# tick per request — build/serialize, wait (the whole server round
# trip), deserialize — so the perf harness can attribute its own side
# of the link; perf/metrics_manager folds the resulting ctpu_prof_*
# series and profview renders them next to the server's.
CLIENT_PROF = PhaseProfiler(name="perf_client")


class BackendKind:
    TRITON_GRPC = "triton_grpc"
    TRITON_HTTP = "triton_http"
    INPROCESS = "inprocess"
    MOCK = "mock"
    # non-KServe protocol families (reference client_backend.h:134-139 lists
    # TENSORFLOW_SERVING and TORCHSERVE next to the Triton kinds)
    TORCHSERVE = "torchserve"
    # TFSERVE speaks gRPC PredictionService (the reference's
    # tfserve_grpc_client.cc shape); the REST variant stays available for
    # endpoints with only the HTTP surface enabled
    TFSERVE = "tfserve"
    TFSERVE_REST = "tfserve_rest"


class ClientBackend:
    """Unified synchronous inference + management surface.

    Latency-critical path is ``infer``; management calls mirror the L3
    clients.  All methods raise InferenceServerException on failure.
    """

    kind = None
    # Replica identity for per-endpoint reporting: the url this backend
    # instance is bound to (multi-replica runs assign one per worker).
    endpoint = ""

    def model_metadata(self, model_name, model_version=""):
        raise NotImplementedError

    def model_config(self, model_name, model_version=""):
        raise NotImplementedError

    def infer(self, model_name, inputs, outputs=None, request_id="",
              sequence_id=0, sequence_start=False, sequence_end=False,
              model_version="", priority=0, timeout_us=None, headers=None):
        """Blocking infer; returns the client's InferResult-like object."""
        raise NotImplementedError

    def statistics(self, model_name="", model_version=""):
        return {}

    def metrics(self):
        """Server utilization metrics snapshot (TPU duty/HBM when exposed)."""
        return {}

    def update_trace_settings(self, model_name="", settings=None):
        """Push trace settings to the server (KServe trace extension);
        non-Triton protocol families have no trace control plane."""
        raise InferenceServerException(
            f"trace settings not supported by the '{self.kind}' backend"
        )

    def register_system_shared_memory(self, name, key, byte_size):
        raise NotImplementedError

    def register_tpu_shared_memory(self, name, raw_handle, device_id, byte_size):
        raise NotImplementedError

    def unregister_shared_memory(self):
        pass

    def close(self):
        pass


class ClientBackendFactory:
    """Create backends by kind+url (client_backend.h:250-307 analog)."""

    @staticmethod
    def create(kind, url=None, engine=None, verbose=False, ssl_options=None,
               **kwargs):
        if kind == BackendKind.TRITON_GRPC:
            return _GrpcBackend(url, verbose, ssl_options=ssl_options)
        if kind == BackendKind.TRITON_HTTP:
            return _HttpBackend(url, verbose, ssl_options=ssl_options)
        if kind == BackendKind.INPROCESS:
            if engine is None:
                raise InferenceServerException(
                    "inprocess backend requires an InferenceEngine"
                )
            return _InprocessBackend(engine)
        if kind == BackendKind.MOCK:
            return MockClientBackend(**kwargs)
        if kind == BackendKind.TORCHSERVE:
            return _TorchServeBackend(url, **kwargs)
        if kind == BackendKind.TFSERVE:
            return _TfServeGrpcBackend(url, **kwargs)
        if kind == BackendKind.TFSERVE_REST:
            return _TfServeBackend(url, **kwargs)
        raise InferenceServerException(f"unknown backend kind '{kind}'")


class _GrpcBackend(ClientBackend):
    kind = BackendKind.TRITON_GRPC

    def __init__(self, url, verbose=False, ssl_options=None):
        import client_tpu.grpc as grpcclient

        opts = ssl_options or {}
        self._mod = grpcclient
        self.endpoint = url
        self._client = grpcclient.InferenceServerClient(
            url,
            verbose=verbose,
            ssl=opts.get("use_ssl", False),
            root_certificates=opts.get("root_certificates"),
            private_key=opts.get("private_key"),
            certificate_chain=opts.get("certificate_chain"),
        )

    def model_metadata(self, model_name, model_version=""):
        return self._client.get_model_metadata(
            model_name, model_version, as_json=True
        )

    def model_config(self, model_name, model_version=""):
        cfg = self._client.get_model_config(model_name, model_version, as_json=True)
        return cfg.get("config", cfg)

    def infer(self, model_name, inputs, outputs=None, request_id="",
              sequence_id=0, sequence_start=False, sequence_end=False,
              model_version="", priority=0, timeout_us=None, headers=None):
        with CLIENT_PROF.start_tick("grpc_client") as ptick:
            with ptick.phase("wait"):  # serialize+rtt+parse live in the lib
                return self._client.infer(
                    model_name,
                    inputs,
                    model_version=model_version,
                    outputs=outputs,
                    request_id=request_id,
                    sequence_id=sequence_id,
                    sequence_start=sequence_start,
                    sequence_end=sequence_end,
                    priority=priority,
                    client_timeout=(timeout_us / 1e6) if timeout_us else None,
                    headers=headers,
                )

    def statistics(self, model_name="", model_version=""):
        return self._client.get_inference_statistics(
            model_name, model_version, as_json=True
        )

    def update_trace_settings(self, model_name="", settings=None):
        return self._client.update_trace_settings(
            model_name=model_name, settings=settings or {}, as_json=True
        )

    def register_system_shared_memory(self, name, key, byte_size):
        self._client.register_system_shared_memory(name, key, byte_size)

    def register_tpu_shared_memory(self, name, raw_handle, device_id, byte_size):
        self._client.register_tpu_shared_memory(
            name, raw_handle, device_id, byte_size
        )

    def unregister_shared_memory(self):
        self._client.unregister_system_shared_memory()
        self._client.unregister_tpu_shared_memory()

    def close(self):
        self._client.close()

    @property
    def infer_input_cls(self):
        return self._mod.InferInput

    @property
    def requested_output_cls(self):
        return self._mod.InferRequestedOutput


class _HttpBackend(_GrpcBackend):
    kind = BackendKind.TRITON_HTTP

    def __init__(self, url, verbose=False, ssl_options=None):
        import client_tpu.http as httpclient

        opts = ssl_options or {}
        ctx = None
        if opts.get("use_ssl") and (
            opts.get("ca_certificates_file")
            or opts.get("client_certificate_file")
        ):
            import ssl as _ssl

            ctx = _ssl.create_default_context(
                cafile=opts.get("ca_certificates_file")
            )
            if opts.get("client_certificate_file"):
                ctx.load_cert_chain(
                    opts["client_certificate_file"],
                    keyfile=opts.get("private_key_file"),
                )
            if not opts.get("verify_peer", True):
                # urllib3 would otherwise set CERT_NONE on a verifying
                # context and raise (check_hostname conflicts)
                ctx.check_hostname = False
                ctx.verify_mode = _ssl.CERT_NONE
        self._mod = httpclient
        self.endpoint = url
        self._client = httpclient.InferenceServerClient(
            url,
            verbose=verbose,
            ssl=opts.get("use_ssl", False),
            ssl_context=ctx,
            insecure=not opts.get("verify_peer", True),
        )

    def update_trace_settings(self, model_name="", settings=None):
        return self._client.update_trace_settings(
            model_name=model_name, settings=settings or {}
        )

    # the HTTP client returns parsed JSON natively (no as_json kwarg); its
    # `timeout` is the KServe per-request server-side timeout in MICROSECONDS
    # (request parameter), not a client deadline like gRPC's client_timeout
    def model_metadata(self, model_name, model_version=""):
        return self._client.get_model_metadata(model_name, model_version)

    def model_config(self, model_name, model_version=""):
        cfg = self._client.get_model_config(model_name, model_version)
        return cfg.get("config", cfg)

    def infer(self, model_name, inputs, outputs=None, request_id="",
              sequence_id=0, sequence_start=False, sequence_end=False,
              model_version="", priority=0, timeout_us=None, headers=None):
        with CLIENT_PROF.start_tick("http_client") as ptick:
            with ptick.phase("wait"):  # serialize+rtt+parse live in the lib
                return self._client.infer(
                    model_name,
                    inputs,
                    model_version=model_version,
                    outputs=outputs,
                    request_id=request_id,
                    sequence_id=sequence_id,
                    sequence_start=sequence_start,
                    sequence_end=sequence_end,
                    priority=priority,
                    timeout=int(timeout_us) if timeout_us else None,
                    headers=headers,
                )

    def statistics(self, model_name="", model_version=""):
        return self._client.get_inference_statistics(model_name, model_version)


class _EngineResult:
    """InferResult-like view over the engine's (response, blobs) tuple so the
    load path (validation, stats) treats all backends uniformly."""

    def __init__(self, response, blobs):
        self._response = response
        self._arrays = {}
        blob_idx = 0
        from client_tpu.utils import from_wire_bytes
        from client_tpu._infer_types import _np_from_json_data

        for out in response.get("outputs", []):
            params = out.get("parameters", {}) or {}
            if "binary_data_size" in params:
                self._arrays[out["name"]] = from_wire_bytes(
                    blobs[blob_idx], out["datatype"], out["shape"]
                )
                blob_idx += 1
            elif "data" in out:
                self._arrays[out["name"]] = _np_from_json_data(
                    out["data"], out["datatype"], out["shape"]
                )
            # shm outputs carry no payload; read them from the region

    def as_numpy(self, name):
        return self._arrays.get(name)

    def get_response(self):
        return self._response


class _InprocessBackend(ClientBackend):
    """Run requests straight into an InferenceEngine — no sockets.

    The analog of the reference's in-process C-API backend
    (triton_c_api/triton_loader.h:84+): benchmark the model/runtime without
    network or serialization overhead.
    """

    kind = BackendKind.INPROCESS

    def __init__(self, engine):
        import client_tpu.grpc as grpcclient

        self._mod = grpcclient
        self._engine = engine

    def update_trace_settings(self, model_name="", settings=None):
        # same normalization point the socket frontends use, so the
        # hermetic path round-trips the identical schema
        return dict(self._engine.update_trace_settings(settings or {}))

    def model_metadata(self, model_name, model_version=""):
        return self._engine.get_model(model_name, model_version).metadata()

    def model_config(self, model_name, model_version=""):
        return self._engine.get_model(model_name, model_version).config()

    def infer(self, model_name, inputs, outputs=None, request_id="",
              sequence_id=0, sequence_start=False, sequence_end=False,
              model_version="", priority=0, timeout_us=None, headers=None):
        ptick = CLIENT_PROF.start_tick("inprocess")
        t_mark = time.perf_counter()
        request = {"id": request_id, "inputs": []}
        if sequence_id:
            request["parameters"] = {
                "sequence_id": sequence_id,
                "sequence_start": bool(sequence_start),
                "sequence_end": bool(sequence_end),
            }
        binary = b""
        for inp in inputs:
            entry = {
                "name": inp.name(),
                "shape": inp.shape(),
                "datatype": inp.datatype(),
            }
            params = dict(inp.parameters())
            if inp.raw_data() is not None:
                binary += inp.raw_data()
            elif inp.nonbinary_data() is not None:
                entry["data"] = inp.nonbinary_data()
            if params:
                entry["parameters"] = params
            request["inputs"].append(entry)
        if outputs:
            request["outputs"] = [
                {"name": o.name(), "parameters": dict(o.parameters())}
                for o in outputs
            ]
        tenant = (headers or {}).get("x-tenant-id", "")
        try:
            ptick.add("serialize", time.perf_counter() - t_mark)
            t_mark = time.perf_counter()
            result = self._engine.execute(
                model_name, model_version, request, binary, tenant=tenant
            )
            ptick.add("wait", time.perf_counter() - t_mark)
            if not isinstance(result, tuple):  # decoupled (generator/list)
                return [_EngineResult(r, b) for r, b in result]
            response, blobs = result
            t_mark = time.perf_counter()
            view = _EngineResult(response, blobs)
            ptick.add("deserialize", time.perf_counter() - t_mark)
            return view
        finally:
            CLIENT_PROF.finish(ptick)

    def statistics(self, model_name="", model_version=""):
        return self._engine.statistics(model_name, model_version)

    def register_system_shared_memory(self, name, key, byte_size):
        self._engine.shm.register_system(name, key, 0, byte_size)

    def register_tpu_shared_memory(self, name, raw_handle, device_id, byte_size):
        self._engine.shm.register_tpu(name, raw_handle, device_id, byte_size)

    def unregister_shared_memory(self):
        self._engine.shm.unregister_system()
        self._engine.shm.unregister_tpu()

    @property
    def infer_input_cls(self):
        return self._mod.InferInput

    @property
    def requested_output_cls(self):
        return self._mod.InferRequestedOutput


class _RestResult:
    """InferResult-like view over a non-KServe JSON prediction response."""

    def __init__(self, arrays, response):
        self._arrays = arrays
        self._response = response

    def as_numpy(self, name):
        return self._arrays.get(name)

    def get_response(self):
        return self._response


class _TorchServeBackend(ClientBackend):
    """TorchServe inference-API backend (reference
    torchserve_http_client.cc:47-225): health via GET /ping, inference via
    POST /predictions/{model} with the input payload as the request body.

    TorchServe has no tensor-metadata endpoint, so (like the reference,
    which requires --input-data for this service kind) the input shape is
    declared at construction: ``input_shape``/``input_datatype`` kwargs, or
    the DataLoader's ``--shape`` override downstream.
    """

    kind = BackendKind.TORCHSERVE

    def __init__(self, url, verbose=False, input_shape=None,
                 input_datatype="FP32", timeout_s=60.0):
        import urllib3

        if "://" not in url:
            url = "http://" + url
        self._base = url.rstrip("/")
        self._http = urllib3.PoolManager(
            maxsize=8, timeout=urllib3.Timeout(total=timeout_s)
        )
        self._shape = list(input_shape or [-1])
        self._datatype = input_datatype

    def _request(self, method, url, **kwargs):
        """urllib3 request with transport errors wrapped — a transient
        connection reset must surface as a per-window error count, not kill
        the sweep via the worker-fatal path (mirrors http/__init__.py)."""
        try:
            return self._http.request(method, url, **kwargs)
        except Exception as e:
            raise InferenceServerException(
                f"{self.kind} {method} {url} failed: {e}", debug_details=e
            ) from e

    @staticmethod
    def _json(r, what):
        try:
            return json.loads(r.data)
        except Exception as e:
            raise InferenceServerException(
                f"{what} returned non-JSON body: {r.data[:200]!r}",
                debug_details=e,
            ) from e

    def _get(self, path):
        r = self._request("GET", self._base + path)
        if r.status != 200:
            raise InferenceServerException(
                f"torchserve GET {path} -> {r.status}: {r.data[:200]!r}",
                status=str(r.status),
            )
        return self._json(r, f"GET {path}")

    def server_live(self):
        return self._get("/ping").get("status") == "Healthy"

    def model_metadata(self, model_name, model_version=""):
        # surface the declared tensor interface in KServe-metadata shape so
        # DataLoader / InferDataManager work unchanged
        return {
            "name": model_name,
            "versions": ["1.0"],
            "platform": "pytorch_torchserve",
            "inputs": [{"name": "data", "datatype": self._datatype,
                        "shape": self._shape}],
            "outputs": [{"name": "predictions", "datatype": "FP64",
                         "shape": [-1]}],
        }

    def model_config(self, model_name, model_version=""):
        models = self._get(f"/models/{model_name}")
        return {"name": model_name, "torchserve": models}

    def infer(self, model_name, inputs, outputs=None, request_id="",
              sequence_id=0, sequence_start=False, sequence_end=False,
              model_version="", priority=0, timeout_us=None, headers=None):
        if not inputs:
            raise InferenceServerException("torchserve infer needs one input")
        body = bytes(inputs[0].raw_data() or b"")
        r = self._request(
            "POST", f"{self._base}/predictions/{model_name}", body=body,
            headers={"Content-Type": "application/octet-stream"},
        )
        if r.status != 200:
            raise InferenceServerException(
                f"torchserve predict -> {r.status}: {r.data[:200]!r}",
                status=str(r.status),
            )
        # A 200 is a successful inference whatever the body shape: numeric
        # predictions become a validatable tensor; anything else (TorchServe
        # classification dicts, text/plain custom handlers) stays reachable
        # via get_response() as parsed JSON or raw bytes.
        try:
            doc = json.loads(r.data)
        except Exception:
            return _RestResult({}, r.data)
        try:
            arrays = {
                "predictions": np.asarray(doc, dtype=np.float64).reshape(-1)
            }
        except (TypeError, ValueError):
            arrays = {}
        return _RestResult(arrays, doc)

    def close(self):
        self._http.clear()

    @property
    def infer_input_cls(self):
        import client_tpu.grpc as grpcclient

        return grpcclient.InferInput

    @property
    def requested_output_cls(self):
        import client_tpu.grpc as grpcclient

        return grpcclient.InferRequestedOutput


class _TfServeGrpcBackend(ClientBackend):
    """TensorFlow-Serving backend over gRPC PredictionService — the
    reference's service shape (tensorflow_serving/tfserve_grpc_client.cc:
    PredictRequest with a TensorProto inputs map, ModelService status for
    liveness).  Wire messages come from the self-contained
    proto/tfserve.proto mirror (field numbers match upstream tensorflow, so
    this talks to a real TF-Serving endpoint)."""

    kind = BackendKind.TFSERVE

    _DTYPES = {
        "FP32": ("DT_FLOAT", np.float32),
        "FP64": ("DT_DOUBLE", np.float64),
        "INT32": ("DT_INT32", np.int32),
        "INT64": ("DT_INT64", np.int64),
        "INT16": ("DT_INT16", np.int16),
        "INT8": ("DT_INT8", np.int8),
        "UINT8": ("DT_UINT8", np.uint8),
        "UINT32": ("DT_UINT32", np.uint32),
        "UINT64": ("DT_UINT64", np.uint64),
        "BOOL": ("DT_BOOL", np.bool_),
    }

    def __init__(self, url, verbose=False, signature_name="serving_default",
                 input_name="input", output_name="output", input_shape=None,
                 input_datatype="FP32", **_):
        import grpc

        from client_tpu._proto import tfserve_pb2 as tfs

        self._tfs = tfs
        self._signature = signature_name
        self._input_name = input_name
        self._output_name = output_name
        self._shape = input_shape or [-1, 4]
        self._datatype = input_datatype
        self._channel = grpc.insecure_channel(url)
        service = "/tensorflow.serving.PredictionService/"
        self._predict = self._channel.unary_unary(
            service + "Predict",
            request_serializer=tfs.PredictRequest.SerializeToString,
            response_deserializer=tfs.PredictResponse.FromString,
        )
        self._metadata_rpc = self._channel.unary_unary(
            service + "GetModelMetadata",
            request_serializer=tfs.GetModelMetadataRequest.SerializeToString,
            response_deserializer=tfs.GetModelMetadataResponse.FromString,
        )
        self._status = self._channel.unary_unary(
            "/tensorflow.serving.ModelService/GetModelStatus",
            request_serializer=tfs.GetModelStatusRequest.SerializeToString,
            response_deserializer=tfs.GetModelStatusResponse.FromString,
        )

    def server_live(self):
        return True  # liveness is per-model (GetModelStatus) below

    def model_ready(self, model_name, model_version=""):
        import grpc

        request = self._tfs.GetModelStatusRequest()
        request.model_spec.name = model_name
        try:
            response = self._status(request)
        except grpc.RpcError as e:
            raise InferenceServerException(
                f"GetModelStatus failed: {e.details()}"
            ) from e
        return any(
            s.state == self._tfs.ModelVersionStatus.AVAILABLE
            for s in response.model_version_status
        )

    def model_metadata(self, model_name, model_version=""):
        import grpc

        request = self._tfs.GetModelMetadataRequest()
        request.model_spec.name = model_name
        request.metadata_field.append("signature_def")
        version = "1"
        try:
            response = self._metadata_rpc(request)
            if response.model_spec.version.value:
                version = str(response.model_spec.version.value)
        except grpc.RpcError:
            pass  # metadata verb optional on some deployments
        return {
            "name": model_name,
            "versions": [version],
            "platform": "tensorflow_serving",
            "inputs": [{"name": self._input_name,
                        "datatype": self._datatype, "shape": self._shape}],
            "outputs": [{"name": self._output_name, "datatype": "FP32",
                         "shape": [-1]}],
        }

    def model_config(self, model_name, model_version=""):
        return {"name": model_name, "platform": "tensorflow_serving"}

    def _to_tensor(self, tensor, inp):
        from client_tpu.utils import from_wire_bytes

        datatype = inp.datatype()
        if datatype == "BYTES":
            arr = from_wire_bytes(inp.raw_data() or b"", "BYTES", inp.shape())
            tensor.dtype = self._tfs.DT_STRING
            for v in arr.flatten():
                tensor.string_val.append(
                    v if isinstance(v, bytes) else str(v).encode()
                )
        else:
            entry = self._DTYPES.get(datatype)
            if entry is None:
                raise InferenceServerException(
                    f"tfserve backend cannot map datatype {datatype}"
                )
            tensor.dtype = getattr(self._tfs, entry[0])
            tensor.tensor_content = inp.raw_data() or b""
        for d in inp.shape():
            tensor.tensor_shape.dim.add().size = int(d)

    def _from_tensor(self, tensor):
        shape = [d.size for d in tensor.tensor_shape.dim]
        for wire, (dt_name, np_dtype) in self._DTYPES.items():
            if tensor.dtype == getattr(self._tfs, dt_name):
                if tensor.tensor_content:
                    arr = np.frombuffer(tensor.tensor_content, dtype=np_dtype)
                else:
                    # upstream's repeated-field conventions: int_val also
                    # carries the narrow integer dtypes
                    field = {
                        "DT_FLOAT": tensor.float_val,
                        "DT_DOUBLE": tensor.double_val,
                        "DT_INT32": tensor.int_val,
                        "DT_INT16": tensor.int_val,
                        "DT_INT8": tensor.int_val,
                        "DT_UINT8": tensor.int_val,
                        "DT_INT64": tensor.int64_val,
                        "DT_UINT32": tensor.uint32_val,
                        "DT_UINT64": tensor.uint64_val,
                        "DT_BOOL": tensor.bool_val,
                    }[dt_name]
                    arr = np.asarray(list(field), dtype=np_dtype)
                return arr.reshape(shape) if shape else arr
        if tensor.dtype == self._tfs.DT_STRING:
            arr = np.array(list(tensor.string_val), dtype=np.object_)
            return arr.reshape(shape) if shape else arr
        raise InferenceServerException(
            f"tfserve response carried unsupported dtype {tensor.dtype}"
        )

    def infer(self, model_name, inputs, outputs=None, request_id="",
              sequence_id=0, sequence_start=False, sequence_end=False,
              model_version="", priority=0, timeout_us=None, headers=None):
        import grpc

        if not inputs:
            raise InferenceServerException("tfserve infer needs inputs")
        request = self._tfs.PredictRequest()
        request.model_spec.name = model_name
        request.model_spec.signature_name = self._signature
        if model_version:
            request.model_spec.version.value = int(model_version)
        for inp in inputs:
            self._to_tensor(request.inputs[inp.name()], inp)
        for out in outputs or []:
            request.output_filter.append(out.name())
        timeout_s = (timeout_us / 1e6) if timeout_us else None
        try:
            response = self._predict(request, timeout=timeout_s)
        except grpc.RpcError as e:
            raise InferenceServerException(
                f"tfserve Predict failed: {e.details()}",
                status=str(e.code().name),
            ) from e
        arrays = {
            name: self._from_tensor(tensor)
            for name, tensor in response.outputs.items()
        }
        return _RestResult(arrays, {"model_spec": response.model_spec.name})

    def statistics(self, model_name="", model_version=""):
        raise NotImplementedError("tensorflow serving exposes no statistics")

    def close(self):
        self._channel.close()

    @property
    def infer_input_cls(self):
        import client_tpu.grpc as grpcclient

        return grpcclient.InferInput

    @property
    def requested_output_cls(self):
        import client_tpu.grpc as grpcclient

        return grpcclient.InferRequestedOutput


class _TfServeBackend(_TorchServeBackend):
    """TensorFlow-Serving backend over its REST predict API — for
    deployments with only the HTTP surface enabled (the gRPC
    PredictionService backend above is the reference's shape)."""

    kind = BackendKind.TFSERVE_REST

    def server_live(self):
        return True  # liveness is per-model below

    def model_metadata(self, model_name, model_version=""):
        md = self._get(f"/v1/models/{model_name}/metadata")
        meta = {
            "name": model_name,
            "versions": [md.get("model_spec", {}).get("version", "1")],
            "platform": "tensorflow_serving",
            "inputs": [{"name": "instances", "datatype": self._datatype,
                        "shape": self._shape}],
            "outputs": [{"name": "predictions", "datatype": "FP64",
                         "shape": [-1]}],
        }
        return meta

    def model_config(self, model_name, model_version=""):
        return {"name": model_name,
                "tfserving": self._get(f"/v1/models/{model_name}")}

    def infer(self, model_name, inputs, outputs=None, request_id="",
              sequence_id=0, sequence_start=False, sequence_end=False,
              model_version="", priority=0, timeout_us=None, headers=None):
        if not inputs:
            raise InferenceServerException("tfserve infer needs one input")
        from client_tpu.utils import from_wire_bytes

        inp = inputs[0]
        arr = from_wire_bytes(
            inp.raw_data() or b"", inp.datatype(), inp.shape()
        )
        doc = {"instances": arr.reshape(arr.shape[0], -1).tolist()
               if arr.ndim > 1 else [arr.tolist()]}
        r = self._request(
            "POST", f"{self._base}/v1/models/{model_name}:predict",
            body=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
        )
        if r.status != 200:
            raise InferenceServerException(
                f"tfserve predict -> {r.status}: {r.data[:200]!r}",
                status=str(r.status),
            )
        out = self._json(r, "predict")
        try:  # columnar ("outputs") or non-numeric responses: raw doc only
            arrays = {
                "predictions": np.asarray(out["predictions"], np.float64)
            }
        except (KeyError, TypeError, ValueError):
            arrays = {}
        return _RestResult(arrays, out)


class MockStats:
    """Request accounting shared by mock backend instances
    (mock_client_backend.h:125-300 analog)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.num_infer_calls = 0
        self.request_timestamps = []
        self.sequence_ids = []

    def record(self, sequence_id):
        with self.lock:
            self.num_infer_calls += 1
            self.request_timestamps.append(time.monotonic_ns())
            if sequence_id:
                self.sequence_ids.append(sequence_id)


class MockClientBackend(ClientBackend):
    """Deterministic fake backend with injectable latency/error schedules."""

    kind = BackendKind.MOCK

    def __init__(self, latency_s=0.0, error_schedule=None, stats=None,
                 metadata=None):
        import client_tpu.grpc as grpcclient

        self._mod = grpcclient
        self.latency_s = latency_s
        self._errors = list(error_schedule or [])  # bool per request: True=fail
        self.stats = stats or MockStats()
        self._metadata = metadata or {
            "name": "mock",
            "versions": ["1"],
            "platform": "mock",
            "inputs": [{"name": "INPUT0", "datatype": "FP32", "shape": [-1, 4]}],
            "outputs": [{"name": "OUTPUT0", "datatype": "FP32", "shape": [-1, 4]}],
        }

    def model_metadata(self, model_name, model_version=""):
        return dict(self._metadata, name=model_name)

    def model_config(self, model_name, model_version=""):
        return {"name": model_name, "max_batch_size": 8}

    def infer(self, model_name, inputs, outputs=None, request_id="",
              sequence_id=0, sequence_start=False, sequence_end=False,
              model_version="", priority=0, timeout_us=None, headers=None):
        self.stats.record(sequence_id)
        if self.latency_s:
            time.sleep(self.latency_s)
        with self.stats.lock:
            fail = self._errors.pop(0) if self._errors else False
        if fail:
            raise InferenceServerException("mock: injected failure")
        return None

    @property
    def infer_input_cls(self):
        return self._mod.InferInput

    @property
    def requested_output_cls(self):
        return self._mod.InferRequestedOutput
