"""Measurement engine: windows, stability detection, summarization.

Parity with the reference InferenceProfiler (reference
src/c++/perf_analyzer/inference_profiler.{h,cc}): per load level, repeat
measurement windows until the last ``stability_window`` trials agree on both
latency and throughput within ``stability_threshold`` percent
(DetermineStability/CheckWindowForStability, inference_profiler.h:365-399),
clipping each window to requests that completed inside it
(ValidLatencyMeasurement, :442), then summarize client percentiles, send
rate, delayed/error counts, and server-side queue/compute deltas from the
statistics endpoint.
"""

import time

import numpy as np

from client_tpu.utils import InferenceServerException


class PerfStatus:
    """Summary of one stabilized load level."""

    def __init__(self, level_label, level_value):
        self.level_label = level_label  # "concurrency" | "request_rate"
        self.level_value = level_value
        self.throughput = 0.0  # infer/sec
        self.latency_avg_us = 0.0
        self.percentiles_us = {}  # 50/90/95/99 -> usec
        self.completed_requests = 0
        self.error_count = 0
        self.delayed_count = 0
        self.send_rate = 0.0
        self.stable = False
        self.server_stats = {}
        self.ensemble_stats = {}  # composing model -> flat counter deltas
        self.tpu_metrics = {}  # gauge -> {avg, max} from MetricsManager
        # multi-replica runs: endpoint -> {count, throughput, avg_us,
        # p99_us, errors} (empty for single-endpoint runs)
        self.per_endpoint = {}
        # --tenants mixes: tenant -> same split (empty below two tenants);
        # the per-tenant p99 is the noisy-neighbor isolation readout
        self.per_tenant = {}
        self.client_window_s = 0.0
        # Fraction of worker-slot wall time NOT spent inside a request —
        # harness bookkeeping + data rotation (reference "perf_analyzer
        # overhead", inference_profiler.h:430-533).
        self.overhead_pct = 0.0
        # --prefix-share sweeps: this level's KV prefix-cache outcome
        # ({"prefix_hit_pct", "prefill_tokens_saved_pct", raw deltas};
        # empty when no prefix probe is wired)
        self.lm_prefix = {}
        # --speculative sweeps: this level's draft/verify outcome
        # ({"spec_acceptance_pct", "spec_tokens_per_sec", raw deltas};
        # empty when no spec probe is wired)
        self.lm_spec = {}

    def latency_us(self, percentile=None):
        if percentile is None:
            return self.latency_avg_us
        return self.percentiles_us.get(percentile, 0.0)


class Measurement:
    __slots__ = ("throughput", "latency_avg_ns", "latencies_ns", "errors",
                 "delayed", "window_s", "send_rate", "busy_ns",
                 "per_endpoint", "per_tenant")

    def __init__(self, throughput, latency_avg_ns, latencies_ns, errors,
                 delayed, window_s, send_rate, busy_ns=0, per_endpoint=None,
                 per_tenant=None):
        self.throughput = throughput
        self.latency_avg_ns = latency_avg_ns
        self.latencies_ns = latencies_ns
        self.errors = errors
        self.delayed = delayed
        self.window_s = window_s
        self.send_rate = send_rate
        self.busy_ns = busy_ns  # total in-request time across worker slots
        # endpoint -> {"latencies_ns": ndarray, "errors": int} for this
        # window (only populated when records carry endpoint identities)
        self.per_endpoint = per_endpoint or {}
        self.per_tenant = per_tenant or {}


class InferenceProfiler:
    def __init__(self, manager, backend=None, measurement_window_s=1.0,
                 max_trials=10, stability_threshold=0.1, stability_window=3,
                 percentile=None, verbose=False, metrics_manager=None,
                 rendezvous=None, measurement_mode="time_windows",
                 measurement_request_count=50):
        """stability_threshold is fractional (0.1 == ±10%, the reference's
        default); percentile selects the latency used for the stability check
        (None = average, reference --percentile).

        measurement_mode: "time_windows" closes each window after
        ``measurement_window_s``; "count_windows" closes it after
        ``measurement_request_count`` completed requests (reference
        --measurement-mode count_windows, inference_profiler.h:430-533),
        with a 10x-window time cap so an idle server cannot hang the sweep.
        """
        self.manager = manager
        self.backend = backend
        self.window_s = measurement_window_s
        self.max_trials = max_trials
        self.threshold = stability_threshold
        self.stability_window = stability_window
        self.percentile = percentile
        self.verbose = verbose
        self.metrics = metrics_manager  # optional MetricsManager
        self.rendezvous = rendezvous  # optional multi-rank coordinator
        if measurement_mode not in ("time_windows", "count_windows"):
            raise InferenceServerException(
                f"unknown measurement mode '{measurement_mode}'"
            )
        self.measurement_mode = measurement_mode
        self.request_count = int(measurement_request_count)
        # optional zero-arg callable returning the LM engine's prefix-
        # cache counters ({hits, misses, prefill_tokens, saved_tokens});
        # wired by the CLI for --prefix-share runs so every sweep level
        # reports its hit rate and prefill savings as a counter DELTA
        self.prefix_probe = None
        # --speculative analogue ({proposed, accepted, lm_tokens}); per
        # level the delta yields acceptance rate and decode tokens/s
        self.spec_probe = None

    # -- one window ----------------------------------------------------------

    def measure(self):
        if self.measurement_mode == "count_windows":
            return self._measure_count()
        window_start = time.monotonic_ns()
        self.manager.get_and_reset_num_sent()
        time.sleep(self.window_s)
        sent = self.manager.get_and_reset_num_sent()
        records = self.manager.swap_timestamps()
        # close the window after the swap so a record completing during the
        # swap itself is never clipped as "future"
        window_end = time.monotonic_ns()
        self.manager.check_health()
        return self._window_measurement(
            records, window_start, window_end, sent
        )

    def _measure_count(self):
        """Close the window once ``request_count`` requests have completed
        inside it (MeasureForCountWindows); capped at 10x the time window so
        a stalled server surfaces as a short, zero-ish measurement instead
        of a hang."""
        window_start = time.monotonic_ns()
        deadline = window_start + int(self.window_s * 10 * 1e9)
        self.manager.get_and_reset_num_sent()
        records = []
        sent = 0
        while True:
            time.sleep(min(0.02, self.window_s))
            sent += self.manager.get_and_reset_num_sent()
            records.extend(self.manager.swap_timestamps())
            now = time.monotonic_ns()
            done = sum(
                1 for r in records
                if r.ok and window_start <= r.end_ns <= now
            )
            if done >= self.request_count or now >= deadline:
                window_end = now
                break
        self.manager.check_health()
        return self._window_measurement(
            records, window_start, window_end, sent
        )

    def _window_measurement(self, records, window_start, window_end, sent):
        # ValidLatencyMeasurement: only requests completing inside the window
        valid = [r for r in records
                 if window_start <= r.end_ns <= window_end and r.ok]
        errors = sum(1 for r in records if not r.ok)
        delayed = sum(1 for r in valid if r.delayed)
        window_s = (window_end - window_start) / 1e9
        lat = np.array([r.end_ns - r.start_ns for r in valid], np.int64)
        # In-request time attributed to the window a request COMPLETES in
        # (full duration, not clipped at window_start): consecutive windows
        # then conserve busy time — clipping both ends would drop the
        # prior-window portion of every in-flight request and overstate
        # harness overhead.  Failed requests count too (the slot was busy).
        busy = sum(
            r.end_ns - r.start_ns
            for r in records
            if r.end_ns <= window_end
        )
        per_endpoint = self._group_window(records, valid, "endpoint")
        per_tenant = self._group_window(records, valid, "tenant")
        return Measurement(
            throughput=len(valid) / window_s if window_s > 0 else 0.0,
            latency_avg_ns=float(lat.mean()) if lat.size else 0.0,
            latencies_ns=lat,
            errors=errors,
            delayed=delayed,
            window_s=window_s,
            send_rate=sent / window_s if window_s > 0 else 0.0,
            busy_ns=int(busy),
            per_endpoint=per_endpoint,
            per_tenant=per_tenant,
        )

    @staticmethod
    def _group_window(records, valid, attr):
        """One window's {group: latencies/errors} split keyed on a record
        attribute — the shared shape behind the per-endpoint (replica) and
        per-tenant (QoS) summaries."""
        groups = {}
        if not any(getattr(r, attr) for r in records):
            return groups
        for r in valid:
            entry = groups.setdefault(
                getattr(r, attr), {"latencies_ns": [], "errors": 0}
            )
            entry["latencies_ns"].append(r.end_ns - r.start_ns)
        for r in records:
            if not r.ok:
                entry = groups.setdefault(
                    getattr(r, attr), {"latencies_ns": [], "errors": 0}
                )
                entry["errors"] += 1
        for entry in groups.values():
            entry["latencies_ns"] = np.asarray(
                entry["latencies_ns"], np.int64
            )
        return groups

    # -- stability loop ------------------------------------------------------

    def _stability_metric(self, m):
        if self.percentile and m.latencies_ns.size:
            return float(np.percentile(m.latencies_ns, self.percentile))
        return m.latency_avg_ns

    def _is_stable(self, window):
        if len(window) < self.stability_window:
            return False
        tps = [m.throughput for m in window]
        lats = [self._stability_metric(m) for m in window]
        if any(m.throughput == 0 for m in window):
            return False
        for series in (tps, lats):
            avg = np.mean(series)
            if avg <= 0:
                return False
            if max(abs(v - avg) / avg for v in series) > self.threshold:
                return False
        return True

    def profile_level(self, label, value):
        """Run windows at the current manager configuration until stable."""
        before_prefix = (
            self.prefix_probe() if self.prefix_probe is not None else None
        )
        before_spec = (
            self.spec_probe() if self.spec_probe is not None else None
        )
        t0 = time.monotonic()
        status = self._profile_level_windows(label, value)
        elapsed_s = time.monotonic() - t0
        if before_prefix is not None:
            status.lm_prefix = self._prefix_delta(before_prefix)
        if before_spec is not None:
            status.lm_spec = self._spec_delta(before_spec, elapsed_s)
        return status

    def _prefix_delta(self, before):
        after = self.prefix_probe()
        delta = {k: after.get(k, 0) - before.get(k, 0) for k in after}
        looked = delta.get("hits", 0) + delta.get("misses", 0)
        prefilled = (
            delta.get("prefill_tokens", 0) + delta.get("saved_tokens", 0)
        )
        return {
            "prefix_hit_pct": (
                round(100.0 * delta.get("hits", 0) / looked, 2)
                if looked else 0.0
            ),
            "prefill_tokens_saved_pct": (
                round(100.0 * delta.get("saved_tokens", 0) / prefilled, 2)
                if prefilled else 0.0
            ),
            **delta,
        }

    def _spec_delta(self, before, elapsed_s):
        after = self.spec_probe()
        delta = {k: after.get(k, 0) - before.get(k, 0) for k in after}
        proposed = delta.get("proposed", 0)
        return {
            "spec_acceptance_pct": (
                round(100.0 * delta.get("accepted", 0) / proposed, 2)
                if proposed else 0.0
            ),
            # delivered LM tokens over the level's wall clock: the
            # speedup readout a spec-on vs spec-off A/B divides
            "spec_tokens_per_sec": (
                round(delta.get("lm_tokens", 0) / elapsed_s, 1)
                if elapsed_s > 0 else 0.0
            ),
            **delta,
        }

    def _profile_level_windows(self, label, value):
        if self.metrics is not None:
            self.metrics.swap_snapshots()  # drop pre-level scrapes
        window = []
        for trial in range(self.max_trials):
            m = self.measure()
            window.append(m)
            if len(window) > self.stability_window:
                window.pop(0)
            if self.verbose:
                print(
                    f"  [trial {trial + 1}] {label}={value} "
                    f"throughput={m.throughput:.1f}/s "
                    f"avg_lat={m.latency_avg_ns / 1e3:.0f}us "
                    f"errors={m.errors}"
                )
            local_stable = self._is_stable(window)
            # multi-rank: keep measuring until EVERY rank stabilizes
            # (reference AllMPIRanksAreStable, inference_profiler.h:537)
            all_stable = (
                self.rendezvous.all_ranks_stable(local_stable)
                if self.rendezvous is not None
                else local_stable
            )
            if all_stable:
                return self._summarize(label, value, window, stable=True)
        # ranks stay in lockstep here: one consensus per trial and identical
        # max_trials means every rank leaves the loop on the same trial
        return self._summarize(label, value, window, stable=False)

    def _summarize(self, label, value, window, stable):
        status = PerfStatus(label, value)
        status.stable = stable
        all_lat = (
            np.concatenate([m.latencies_ns for m in window])
            if window else np.array([], np.int64)
        )
        status.completed_requests = int(all_lat.size)
        status.client_window_s = sum(m.window_s for m in window)
        status.throughput = float(np.mean([m.throughput for m in window]))
        status.send_rate = float(np.mean([m.send_rate for m in window]))
        status.error_count = sum(m.errors for m in window)
        status.delayed_count = sum(m.delayed for m in window)
        if all_lat.size:
            status.latency_avg_us = float(all_lat.mean()) / 1e3
            wanted = {50, 90, 95, 99}
            if self.percentile:
                wanted.add(self.percentile)  # the stability-governing one
            for p in sorted(wanted):
                status.percentiles_us[p] = float(np.percentile(all_lat, p)) / 1e3
        # Harness overhead is only meaningful for concurrency mode, where a
        # slot is meant to be saturated; request-rate workers idle between
        # scheduled sends BY DESIGN, so the ratio would just measure pacing.
        slots = int(getattr(self.manager, "concurrency", 0) or 0)
        total_slot_ns = sum(m.window_s for m in window) * slots * 1e9
        if label == "concurrency" and total_slot_ns > 0:
            busy = sum(m.busy_ns for m in window)
            status.overhead_pct = round(
                max(0.0, 100.0 * (1.0 - busy / total_slot_ns)), 2
            )
        status.per_endpoint = self._group_summary(window, "per_endpoint")
        status.per_tenant = self._group_summary(window, "per_tenant")
        if self.metrics is not None:
            status.tpu_metrics = self.metrics.summarize(
                self.metrics.swap_snapshots()
            )
        return status

    @staticmethod
    def _group_summary(window, attr):
        """Aggregate the windows' grouped measurements (``per_endpoint`` or
        ``per_tenant``) into the summary's throughput/latency split (only
        meaningful past one group)."""
        groups = sorted({g for m in window for g in getattr(m, attr)})
        if len(groups) < 2:
            return {}
        total_s = sum(m.window_s for m in window)
        out = {}
        for group in groups:
            lat = [
                getattr(m, attr)[group]["latencies_ns"]
                for m in window
                if group in getattr(m, attr)
            ]
            lat = (
                np.concatenate([a for a in lat if a.size] or
                               [np.array([], np.int64)])
            )
            errors = sum(
                getattr(m, attr).get(group, {}).get("errors", 0)
                for m in window
            )
            out[group] = {
                "count": int(lat.size),
                "throughput": lat.size / total_s if total_s > 0 else 0.0,
                "avg_us": float(lat.mean()) / 1e3 if lat.size else 0.0,
                "p99_us": (
                    float(np.percentile(lat, 99)) / 1e3 if lat.size else 0.0
                ),
                "errors": int(errors),
            }
        return out

    def profile_completion(self, concurrency, window_s=8.0, warmup_s=2.0):
        """Drain-corrected completion throughput for asynchronous-dispatch
        transports (TPU shm).

        A TPU-shm request is acked at device *dispatch*; on hardware where
        dispatch outruns execution the ack rate overstates real throughput.
        This mode runs one fixed window at ``concurrency``, then stops the
        workers and drains (``data_manager.sync_outputs()`` — D2H visibility
        of every output region) before closing the clock, so the reported
        infer/sec counts only device work that actually completed.  Latency
        percentiles are still ack latencies (the per-request completion
        variant is ``--tpu-shm-sync``)."""
        self.manager.change_concurrency_level(concurrency)
        time.sleep(warmup_s)
        self.manager.swap_timestamps()
        self.manager.get_and_reset_num_sent()
        t0 = time.monotonic_ns()
        time.sleep(window_s)
        self.manager.stop_workers()
        sync = getattr(self.manager.data_manager, "sync_outputs", None)
        if sync is not None:
            sync()
        t1 = time.monotonic_ns()
        records = self.manager.swap_timestamps()
        sent = self.manager.get_and_reset_num_sent()
        status = PerfStatus("concurrency", concurrency)
        ok = [r for r in records if r.ok]
        lat = np.array([r.end_ns - r.start_ns for r in ok], np.int64)
        elapsed = (t1 - t0) / 1e9
        status.throughput = len(ok) / elapsed if elapsed > 0 else 0.0
        status.completed_requests = len(ok)
        status.client_window_s = elapsed
        status.error_count = len(records) - len(ok)
        status.send_rate = sent / elapsed if elapsed > 0 else 0.0
        status.stable = True  # single drained window: no stability loop
        if lat.size:
            status.latency_avg_us = float(lat.mean()) / 1e3
            for p in (50, 90, 95, 99):
                status.percentiles_us[p] = float(np.percentile(lat, p)) / 1e3
        if self.metrics is not None:
            status.tpu_metrics = self.metrics.summarize(
                self.metrics.swap_snapshots()
            )
        return status

    # -- search over load levels ---------------------------------------------

    def profile_concurrency_range(self, start, end, step, latency_limit_us=None):
        """Linear sweep (reference Profile<size_t>, inference_profiler.h:243)."""
        results = []
        c = start
        while c <= end:
            self.manager.change_concurrency_level(c)
            before = self._server_stats()
            before_ens = self._ensemble_stats()
            status = self.profile_level("concurrency", c)
            status.server_stats = self._server_stats_delta(before)
            status.ensemble_stats = self._ensemble_stats_delta(before_ens)
            results.append(status)
            if latency_limit_us and status.latency_us(
                self.percentile
            ) > latency_limit_us:
                break
            c += step
        return results

    def profile_request_rate_range(self, start, end, step,
                                   latency_limit_us=None):
        results = []
        r = start
        while r <= end:
            self.manager.change_request_rate(r)
            before = self._server_stats()
            before_ens = self._ensemble_stats()
            status = self.profile_level("request_rate", r)
            status.server_stats = self._server_stats_delta(before)
            status.ensemble_stats = self._ensemble_stats_delta(before_ens)
            results.append(status)
            if latency_limit_us and status.latency_us(
                self.percentile
            ) > latency_limit_us:
                break
            r += step
        return results

    def profile_concurrency_binary(self, start, end, latency_limit_us):
        """Binary search for max concurrency under the latency limit
        (SearchMode::BINARY)."""
        results = []
        lo, hi = start, end
        best = None
        while lo <= hi:
            mid = (lo + hi) // 2
            self.manager.change_concurrency_level(mid)
            status = self.profile_level("concurrency", mid)
            results.append(status)
            if status.latency_us(self.percentile) <= latency_limit_us:
                best = status
                lo = mid + 1
            else:
                hi = mid - 1
        return results, best

    def profile_request_rate_binary(self, start, end, latency_limit_us,
                                    resolution=None):
        """SLO-seeking search over REQUEST RATE: the max sustainable
        open-loop req/s whose stabilized latency (``percentile`` when
        set, else the average) stays under ``latency_limit_us``.

        Concurrency search answers "how many outstanding requests fit";
        this answers the capacity-planning question — "what arrival rate
        can I advertise under my p99 SLO" — on the open-loop schedule
        whose queueing collapse closed-loop concurrency sweeps hide.
        Bisects [start, end] to ``resolution`` req/s (default: 1/16 of
        the span); returns (all measured levels, best passing level or
        None when even ``start`` violates the SLO).
        """
        lo, hi = float(start), float(end)
        if resolution is None or resolution <= 0:
            resolution = max((hi - lo) / 16.0, 1e-3)

        def measure(rate):
            self.manager.change_request_rate(rate)
            before = self._server_stats()
            before_ens = self._ensemble_stats()
            status = self.profile_level("request_rate", round(rate, 3))
            status.server_stats = self._server_stats_delta(before)
            status.ensemble_stats = self._ensemble_stats_delta(before_ens)
            return status

        results = []
        # probe start explicitly: bisection midpoints never reach lo, so
        # without this a capacity at/just above `start` would be reported
        # as "no passing rate" instead of `start` itself
        status = measure(lo)
        results.append(status)
        if status.latency_us(self.percentile) > latency_limit_us:
            return results, None
        best = status
        while hi - lo >= resolution:
            mid = (lo + hi) / 2.0
            status = measure(mid)
            results.append(status)
            if status.latency_us(self.percentile) <= latency_limit_us:
                best = status
                lo = mid
            else:
                hi = mid
        return results, best

    # -- server-side stats ---------------------------------------------------

    def _server_stats(self):
        if self.backend is None:
            return {}
        try:
            stats = self.backend.statistics(self.manager.model_name)
        except (InferenceServerException, NotImplementedError):
            return {}
        return _flatten_stats(stats)

    def _server_stats_delta(self, before):
        after = self._server_stats()
        return {
            k: after.get(k, 0) - before.get(k, 0)
            for k in after
        }

    # -- ensemble recursion (reference EnsembleDurations,
    #    inference_profiler.h:77-120) ----------------------------------------

    def _composing_models(self):
        """Transitive composing-model names of the swept model, resolved once
        per profiler (the topology is static across a sweep) via ModelParser
        — the single implementation of the ensemble walk."""
        cached = getattr(self, "_composing_cache", None)
        if cached is not None:
            return cached
        composing = []
        if self.backend is not None:
            from client_tpu.perf.model_parser import ModelParser

            try:
                composing = ModelParser.create(
                    self.backend, self.manager.model_name
                ).composing_models
            except (InferenceServerException, NotImplementedError, KeyError):
                composing = []
        self._composing_cache = composing
        return composing

    def _ensemble_stats(self):
        """Flat counters per composing model of the swept ensemble (empty for
        non-ensemble models)."""
        composing = self._composing_models()
        out = {}
        for name in composing:
            try:
                out[name] = _flatten_stats(self.backend.statistics(name))
            except (InferenceServerException, NotImplementedError):
                out[name] = {}
        return out

    def _ensemble_stats_delta(self, before):
        after = self._ensemble_stats()
        return {
            name: {
                k: counters.get(k, 0) - before.get(name, {}).get(k, 0)
                for k in counters
            }
            for name, counters in after.items()
        }


def _flatten_stats(stats):
    """Normalize a statistics() response into flat counters (ns totals).
    Accepts the wire shape ({"model_stats": [...]}) and the in-process
    engine's bare list of per-model entries."""
    out = {}
    if isinstance(stats, dict):
        model_stats = stats.get("model_stats", [])
    elif isinstance(stats, list):
        model_stats = stats
    else:
        model_stats = []
    for ms in model_stats:
        agg = ms.get("inference_stats", {})
        for phase in ("success", "queue", "compute_input", "compute_infer",
                      "compute_output", "cache_hit", "cache_miss"):
            entry = agg.get(phase, {})
            out[f"{phase}_count"] = out.get(f"{phase}_count", 0) + int(
                entry.get("count", 0)
            )
            out[f"{phase}_ns"] = out.get(f"{phase}_ns", 0) + int(
                entry.get("ns", 0)
            )
    return out
