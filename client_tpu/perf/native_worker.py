"""Driver for the native C++ load-generation worker (build/cpp/perf_worker).

The binary is the harness's C++ engine — the reference perf_analyzer's
native load path (perf_analyzer.cc:56-424): N async InferContexts
multiplexed on one HTTP/2 connection, completed by its reactor thread.  No
GIL anywhere near the measurement; the Python side only assembles arguments
and parses the one-line JSON report.

TPU-shm loads compose with region-by-name referencing exactly like
procpool: the coordinator (Python, owns jax) creates and registers the
regions; the native worker sends requests that reference them by name.
"""

import json
import os
import subprocess

from client_tpu.utils import InferenceServerException

_DEFAULT_BINARY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "build", "cpp", "perf_worker",
)


def native_worker_available(binary=None):
    return os.path.exists(binary or _DEFAULT_BINARY)


def native_windows_stable(windows, threshold, window_count=3):
    """DetermineStability over trailing native windows (reference
    inference_profiler.h:365-399): throughput and p99 latency of the last
    ``window_count`` windows each within ±threshold of their mean.  Shared
    by the perf CLI sweep and bench.py's headline qualification."""
    if len(windows) < window_count:
        return False
    tail = windows[-window_count:]
    for key in ("throughput", "p99_us"):
        vals = [w[key] for w in tail]
        avg = sum(vals) / len(vals)
        if avg <= 0 or any(abs(v - avg) > threshold * avg for v in vals):
            return False
    return True


def run_native_worker(url, model_name, *, concurrency, duration_s,
                      warmup_s=1.0, wire_inputs=(), shm_inputs=(),
                      shm_outputs=(), binary=None, timeout_s=None,
                      request_rate=0.0, distribution="constant",
                      window_interval_s=0.0, completion_sync=False,
                      sequences=0, seq_steps=8, decoupled=False):
    """One native measurement (fixed concurrency, request-rate schedule, or
    bidi sequence streaming).

    wire_inputs: [(name, datatype, shape)] — random bytes generated in the
    worker.  shm_inputs: [(name, datatype, shape, region, nbytes)].
    shm_outputs: [(name, region, nbytes)].

    request_rate > 0 switches the worker to an open-loop schedule
    (constant or poisson inter-arrivals) with `concurrency` capping the
    outstanding requests; the report then carries a ``delayed`` count.
    completion_sync requests wire outputs instead of shm outputs, so every
    recorded latency covers device compute + D2H (completion, not ack).
    sequences > 0 drives that many stateful sequences of seq_steps over the
    bidi stream instead of unary AsyncInfer.  decoupled drives
    N-responses-per-request streaming (the LLM token-stream shape): latency
    samples are time-to-first-response, completion rides the
    triton_final_response marker, and the report carries the total content
    ``responses`` count.  wire_inputs entries may carry a constant fill as
    a 4th element (name, datatype, shape, value) — required for decoupled
    models whose input encodes the response count.

    Returns the worker's final report dict (ok/errors/delayed/elapsed_s/
    throughput/p50_us/.../avg_us/mode); with window_interval_s > 0 the
    report also carries the per-window records under ``windows`` — the
    feed for the profiler's stability loop over native load.
    """
    binary = binary or _DEFAULT_BINARY
    if not os.path.exists(binary):
        raise InferenceServerException(
            f"native perf worker not built: {binary} (run `make`)"
        )
    cmd = [binary, "-u", url, "-m", model_name, "-c", str(concurrency),
           "-d", str(duration_s), "-w", str(warmup_s)]
    if request_rate > 0:
        cmd += ["-r", str(request_rate), "--distribution", distribution]
    if window_interval_s > 0:
        cmd += ["--window-interval", str(window_interval_s)]
    if completion_sync:
        cmd += ["--completion-sync"]
    if sequences > 0:
        cmd += ["--sequences", str(sequences), "--seq-steps", str(seq_steps)]
    if decoupled:
        cmd += ["--decoupled"]
    for entry in wire_inputs:
        name, datatype, shape = entry[0], entry[1], entry[2]
        dims = ",".join(str(int(d)) for d in shape)
        fill = f"={int(entry[3])}" if len(entry) > 3 else ""
        cmd += ["--wire-input", f"{name}:{datatype}:{dims}{fill}"]
    for name, datatype, shape, region, nbytes in shm_inputs:
        dims = ",".join(str(int(d)) for d in shape)
        cmd += ["--shm-input", f"{name}:{datatype}:{dims}:{region}:{nbytes}"]
    for name, region, nbytes in shm_outputs:
        cmd += ["--shm-output", f"{name}:{region}:{nbytes}"]
    proc = subprocess.run(
        cmd, capture_output=True, text=True,
        timeout=timeout_s or (warmup_s + duration_s + 90),
    )
    if proc.returncode != 0:
        raise InferenceServerException(
            f"native perf worker failed ({proc.returncode}): "
            f"{proc.stderr.strip() or proc.stdout.strip()}"
        )
    try:
        lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
        report = json.loads(lines[-1])
        windows = []
        for ln in lines[:-1]:
            try:
                doc = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if "window" in doc:
                windows.append(doc)
        if windows:
            report["windows"] = windows
        return report
    except (json.JSONDecodeError, IndexError) as e:
        raise InferenceServerException(
            f"malformed native worker report: {proc.stdout!r}"
        ) from e
