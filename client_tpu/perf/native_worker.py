"""Driver for the native C++ load-generation worker (build/cpp/perf_worker).

The binary is the harness's C++ engine — the reference perf_analyzer's
native load path (perf_analyzer.cc:56-424): N async InferContexts
multiplexed on one HTTP/2 connection, completed by its reactor thread.  No
GIL anywhere near the measurement; the Python side only assembles arguments
and parses the one-line JSON report.

TPU-shm loads compose with region-by-name referencing exactly like
procpool: the coordinator (Python, owns jax) creates and registers the
regions; the native worker sends requests that reference them by name.
"""

import json
import os
import subprocess

from client_tpu.utils import InferenceServerException

_DEFAULT_BINARY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "build", "cpp", "perf_worker",
)


def native_worker_available(binary=None):
    return os.path.exists(binary or _DEFAULT_BINARY)


def run_native_worker(url, model_name, *, concurrency, duration_s,
                      warmup_s=1.0, wire_inputs=(), shm_inputs=(),
                      shm_outputs=(), binary=None, timeout_s=None):
    """One fixed-concurrency native measurement.

    wire_inputs: [(name, datatype, shape)] — random bytes generated in the
    worker.  shm_inputs: [(name, datatype, shape, region, nbytes)].
    shm_outputs: [(name, region, nbytes)].  Returns the worker's report
    dict: ok/errors/elapsed_s/throughput/p50_us/.../avg_us.
    """
    binary = binary or _DEFAULT_BINARY
    if not os.path.exists(binary):
        raise InferenceServerException(
            f"native perf worker not built: {binary} (run `make`)"
        )
    cmd = [binary, "-u", url, "-m", model_name, "-c", str(concurrency),
           "-d", str(duration_s), "-w", str(warmup_s)]
    for name, datatype, shape in wire_inputs:
        dims = ",".join(str(int(d)) for d in shape)
        cmd += ["--wire-input", f"{name}:{datatype}:{dims}"]
    for name, datatype, shape, region, nbytes in shm_inputs:
        dims = ",".join(str(int(d)) for d in shape)
        cmd += ["--shm-input", f"{name}:{datatype}:{dims}:{region}:{nbytes}"]
    for name, region, nbytes in shm_outputs:
        cmd += ["--shm-output", f"{name}:{region}:{nbytes}"]
    proc = subprocess.run(
        cmd, capture_output=True, text=True,
        timeout=timeout_s or (warmup_s + duration_s + 90),
    )
    if proc.returncode != 0:
        raise InferenceServerException(
            f"native perf worker failed ({proc.returncode}): "
            f"{proc.stderr.strip() or proc.stdout.strip()}"
        )
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError) as e:
        raise InferenceServerException(
            f"malformed native worker report: {proc.stdout!r}"
        ) from e
