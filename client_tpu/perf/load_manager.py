"""Load generation: worker threads driving a ClientBackend.

Parity with the reference's load-manager family (reference
src/c++/perf_analyzer/load_manager.h:43-126, concurrency_manager.h:53-119,
request_rate_manager, custom_load_manager, the worker classes and
infer_context.h:43-156), re-shaped for Python: each outstanding request slot
is a worker thread (the sync-client analog of an InferContext), timestamps
accumulate per-thread and are swapped out by the profiler between
measurement windows.
"""

import threading
import time

import numpy as np

from client_tpu.utils import InferenceServerException


class RequestRecord:
    __slots__ = ("start_ns", "end_ns", "ok", "sequence_id", "delayed",
                 "endpoint", "tenant")

    def __init__(self, start_ns, end_ns, ok, sequence_id=0, delayed=False,
                 endpoint="", tenant=""):
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.ok = ok
        self.sequence_id = sequence_id
        self.delayed = delayed
        # replica this request was sent to (multi-replica runs report a
        # per-endpoint throughput/latency split)
        self.endpoint = endpoint
        # tenant identity this request was sent AS (--tenants mixes report
        # a per-tenant latency split — the noisy-neighbor isolation proof)
        self.tenant = tenant


class ThreadStat:
    """Per-worker request records + health (infer_context.h ThreadStat).

    ``fatal`` is set only for errors that kill the worker loop (backend
    construction/transport collapse); per-request failures are recorded in
    ``records`` and surface as error counts, not aborts.
    """

    def __init__(self):
        self.lock = threading.Lock()
        self.records = []
        self.fatal = None


class InferContext:
    """One request slot: prepared data rotation + send (infer_context.h:43)."""

    def __init__(self, ctx_id, backend, data_manager, loader, model_name,
                 model_version, sequence_manager=None, thread_stat=None,
                 tenant=""):
        self.ctx_id = ctx_id
        self.backend = backend
        self.data_manager = data_manager
        self.loader = loader
        self.model_name = model_name
        self.model_version = model_version
        self.sequences = sequence_manager
        self.stat = thread_stat or ThreadStat()
        self.tenant = tenant  # sent as the x-tenant-id header when set
        self._rot = 0  # (stream, step) rotation for stateless workloads

    def send(self, delayed=False):
        seq_id, seq_start, seq_end = 0, False, False
        if self.sequences is not None:
            status = self.sequences.get(self.ctx_id)
            if status is None or status.remaining_queries <= 0:
                steps_per_stream = [
                    self.loader.num_steps(s)
                    for s in range(self.loader.num_streams)
                ]
                status = self.sequences.begin_sequence(
                    self.ctx_id, steps_per_stream
                )
            stream_id = status.data_stream_id
            step_id = status.step_id % self.loader.num_steps(stream_id)
            seq_id = status.seq_id
            seq_start, seq_end = self.sequences.advance(status)
        else:
            stream_id = self._rot % self.loader.num_streams
            step_id = self._rot // self.loader.num_streams % self.loader.num_steps(
                stream_id
            )
            self._rot += 1
        data = self.data_manager.get_infer_data(stream_id, step_id)
        headers = {"x-tenant-id": self.tenant} if self.tenant else None
        start = time.monotonic_ns()
        ok = True
        try:
            result = self.backend.infer(
                self.model_name,
                data.inputs,
                outputs=data.outputs,
                sequence_id=seq_id,
                sequence_start=seq_start,
                sequence_end=seq_end,
                model_version=self.model_version,
                headers=headers,
            )
            if getattr(self.data_manager, "completion_sync", False):
                self.data_manager.sync_outputs()
            ok = self._validate(result, stream_id, step_id)
        except InferenceServerException:
            ok = False  # counted per-window; does not abort the run
        end = time.monotonic_ns()
        with self.stat.lock:
            self.stat.records.append(
                RequestRecord(
                    start, end, ok, seq_id, delayed,
                    endpoint=self.backend.endpoint,
                    tenant=self.tenant,
                )
            )

    def _validate(self, result, stream_id, step_id):
        return _validate_result(self.loader, result, stream_id, step_id)


def _validate_result(loader, result, stream_id, step_id):
    """Compare response tensors against the data loader's expected-output
    (validation_data) entries, when provided — shared by the sync and async
    request slots."""
    expected = loader.get_expected_outputs(stream_id, step_id)
    if not expected or result is None or not hasattr(result, "as_numpy"):
        return True
    try:
        for name, td in expected.items():
            got = result.as_numpy(name)
            if got is None:
                # output not in the response payload (e.g. delivered via
                # a shared-memory region) — nothing to compare against
                continue
            want = td.array
            if got.size != want.size:
                return False
            if got.dtype == np.object_ or want.dtype == np.object_:
                if list(got.flatten()) != list(want.flatten()):
                    return False
            elif not np.allclose(
                got.reshape(-1).astype(np.float64),
                want.reshape(-1).astype(np.float64),
                rtol=1e-5, atol=1e-6,
            ):
                return False
    except Exception:
        return False  # malformed comparison counts as a failed request
    return True


class LoadManager:
    """Base: owns backend(s), data pipeline, worker threads, stat swap."""

    def __init__(self, backend_factory, data_loader, data_manager, model_name,
                 model_version="", sequence_manager=None, max_threads=16,
                 tenants=None):
        self._backend_factory = backend_factory  # () -> ClientBackend
        self.loader = data_loader
        self.data_manager = data_manager
        self.model_name = model_name
        self.model_version = model_version
        self.sequences = sequence_manager
        self.max_threads = max_threads
        # Tenant mix: worker slot i sends as tenants[i % len(tenants)]
        # (--tenants "gold:3,bronze:1" expands to a weighted slot list)
        self.tenants = list(tenants or [])
        self._threads = []  # (thread, ThreadStat, stop_event)
        self._backends = []
        self._residual = []  # records harvested from stopped workers
        self._sent = 0
        self._sent_lock = threading.Lock()

    # -- stats ---------------------------------------------------------------

    def swap_timestamps(self):
        """Collect and clear all worker records (load_manager.h SwapTimestamps)."""
        out = self._residual
        self._residual = []
        for _, stat, _ in self._threads:
            with stat.lock:
                out.extend(stat.records)
                stat.records = []
        return out

    def count_sent(self, n=1):
        with self._sent_lock:
            self._sent += n

    def get_and_reset_num_sent(self):
        with self._sent_lock:
            n = self._sent
            self._sent = 0
            return n

    def check_health(self):
        """Raise only on fatal worker conditions: a crashed thread or a
        worker-level error (load_manager.h CheckHealth); per-request failures
        are reported through the measurement error counts instead."""
        for th, stat, stop in self._threads:
            with stat.lock:
                if stat.fatal is not None:
                    raise stat.fatal
            if not th.is_alive() and not stop.is_set():
                raise InferenceServerException(
                    "a load worker thread died unexpectedly"
                )

    # -- worker plumbing -----------------------------------------------------

    def _spawn(self, target, ctx_id):
        stop = threading.Event()
        stat = ThreadStat()
        backend = self._backend_factory()
        self._backends.append(backend)
        tenant = (
            self.tenants[ctx_id % len(self.tenants)] if self.tenants else ""
        )
        ctx = InferContext(
            ctx_id, backend, self.data_manager, self.loader, self.model_name,
            self.model_version, self.sequences, stat, tenant=tenant,
        )

        def run(ctx=ctx, stop=stop, stat=stat):
            try:
                target(ctx, stop)
            except Exception as e:  # worker-level collapse is fatal
                with stat.lock:
                    stat.fatal = e

        th = threading.Thread(target=run, daemon=True)
        self._threads.append((th, stat, stop))
        th.start()

    def stop_workers(self):
        for _, _, stop in self._threads:
            stop.set()
        for th, _, _ in self._threads:
            th.join(timeout=30)
        # Records from the final in-flight requests outlive the worker list:
        # profile_completion stops workers (quiescing sends before the drain)
        # and only then swaps timestamps.
        for _, stat, _ in self._threads:
            with stat.lock:
                self._residual.extend(stat.records)
                stat.records = []
        self._threads = []
        for b in self._backends:
            try:
                b.close()
            except Exception:
                pass
        self._backends = []

    def cleanup(self):
        self.stop_workers()
        self.data_manager.cleanup()


class ConcurrencyManager(LoadManager):
    """Maintain N outstanding requests (concurrency_manager.h:53-119).

    Python shape: one worker thread per outstanding slot (the transports are
    synchronous), so the achievable concurrency equals the thread count.
    Levels beyond ``max_threads`` are refused rather than silently capped —
    raise ``--max-threads`` for bigger sweeps.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.concurrency = 0

    def change_concurrency_level(self, concurrency):
        if concurrency > self.max_threads:
            raise InferenceServerException(
                f"concurrency {concurrency} exceeds max_threads "
                f"{self.max_threads}; raise --max-threads"
            )
        self.stop_workers()
        # A new level starts with a clean slate: tail records the old level's
        # workers produced after its last swap belong to no window (they
        # would otherwise be counted as this level's errors).
        self._residual = []
        self.concurrency = concurrency
        for slot in range(concurrency):
            self._spawn(self._worker_loop, slot)

    def _worker_loop(self, ctx, stop):
        while not stop.is_set():
            ctx.send()
            self.count_sent()


class AsyncConcurrencyManager(LoadManager):
    """N outstanding requests as asyncio tasks on ONE event-loop thread over
    the grpc.aio client — the reference's ``-a/--async`` mode (async
    InferContext slots on the completion-queue thread,
    infer_context.cc:103-150).  Versus thread-per-slot, high concurrency
    costs coroutines instead of OS threads and the GIL is held by a single
    loop, so the measurement instrument stays honest at deep concurrency.

    Stateless workloads only (the reference's async mode pairs sequences
    with streaming, which rides ``async_stream_infer`` instead).
    """

    def __init__(self, url, data_loader, data_manager, model_name,
                 model_version="", max_threads=512):
        super().__init__(
            backend_factory=lambda: None,
            data_loader=data_loader,
            data_manager=data_manager,
            model_name=model_name,
            model_version=model_version,
            max_threads=max_threads,
        )
        self._url = url
        self.concurrency = 0
        self._loop = None
        self._loop_thread = None
        self._client = None
        self._slots = []  # (asyncio.Task, ThreadStat, threading.Event)
        self._loop_error = None

    # -- loop plumbing ------------------------------------------------------

    def _ensure_loop(self):
        import asyncio

        if self._loop is not None:
            return
        # the loop object is created HERE (caller thread) so self._loop
        # is only ever written caller-side (_ensure_loop/cleanup); the
        # pump thread works through its closure, never through self
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)
            started.set()
            try:
                loop.run_forever()
            finally:
                loop.close()

        self._loop = loop
        self._loop_thread = threading.Thread(
            target=run, name="perf-aio-loop", daemon=True
        )
        self._loop_thread.start()
        started.wait()

    def _call_in_loop(self, coro, timeout=60):
        import asyncio

        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            timeout
        )

    async def _get_client(self):
        if self._client is None:
            from client_tpu.grpc import aio as aiogrpc

            self._client = aiogrpc.InferenceServerClient(self._url)
        return self._client

    # -- slots --------------------------------------------------------------

    async def _slot(self, ctx_id, stat, stop):
        client = await self._get_client()
        rot = ctx_id  # interleave (stream, step) rotation across slots
        while not stop.is_set():
            stream_id = rot % self.loader.num_streams
            step_id = (
                rot // self.loader.num_streams
                % self.loader.num_steps(stream_id)
            )
            rot += 1
            data = self.data_manager.get_infer_data(stream_id, step_id)
            start = time.monotonic_ns()
            ok = True
            try:
                result = await client.infer(
                    self.model_name,
                    data.inputs,
                    outputs=data.outputs,
                    model_version=self.model_version,
                )
                if getattr(self.data_manager, "completion_sync", False):
                    self.data_manager.sync_outputs()
                ok = _validate_result(
                    self.loader, result, stream_id, step_id
                )
            except InferenceServerException:
                ok = False
            except Exception as e:  # noqa: BLE001 - transport collapse
                with stat.lock:
                    stat.fatal = e
                return
            end = time.monotonic_ns()
            with stat.lock:
                stat.records.append(
                    RequestRecord(start, end, ok, endpoint=self._url)
                )
            self.count_sent()

    def change_concurrency_level(self, concurrency):
        import asyncio

        if concurrency > self.max_threads:
            raise InferenceServerException(
                f"concurrency {concurrency} exceeds max_threads "
                f"{self.max_threads}; raise --max-threads"
            )
        self.stop_workers()
        self._residual = []  # see ConcurrencyManager.change_concurrency_level
        self._ensure_loop()
        self.concurrency = concurrency

        async def start_slots():
            slots = []
            for ctx_id in range(concurrency):
                stat = ThreadStat()
                stop = threading.Event()
                task = asyncio.get_running_loop().create_task(
                    self._slot(ctx_id, stat, stop)
                )
                slots.append((task, stat, stop))
            return slots

        self._slots = self._call_in_loop(start_slots())

    def stop_workers(self):
        import asyncio

        if not self._slots:
            return
        for _, _, stop in self._slots:
            stop.set()

        async def join_slots(timeout):
            tasks = [task for task, _, _ in self._slots]
            done, pending = await asyncio.wait(tasks, timeout=timeout)
            for task in pending:  # wedged in a hung infer: cancel and move on
                task.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)

        try:
            self._call_in_loop(join_slots(30), timeout=60)
        except Exception:
            pass  # teardown continues; records already harvested below
        for _, stat, _ in self._slots:
            with stat.lock:
                self._residual.extend(stat.records)
                stat.records = []
        self._slots = []

    def swap_timestamps(self):
        out = self._residual
        self._residual = []
        for _, stat, _ in self._slots:
            with stat.lock:
                out.extend(stat.records)
                stat.records = []
        return out

    def check_health(self):
        for task, stat, stop in self._slots:
            with stat.lock:
                if stat.fatal is not None:
                    raise stat.fatal
            if task.done() and not stop.is_set():
                exc = None
                if not task.cancelled():
                    exc = task.exception()  # the slot's real failure
                if exc is not None:
                    raise exc
                raise InferenceServerException(
                    "an async load slot exited unexpectedly"
                )

    def cleanup(self):
        self.stop_workers()
        if self._loop is not None:
            if self._client is not None:
                try:
                    self._call_in_loop(self._client.close(), timeout=10)
                except Exception:
                    pass
                self._client = None
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=10)
            self._loop = None
            self._loop_thread = None
        self.data_manager.cleanup()


class RequestRateManager(LoadManager):
    """Send on a schedule: poisson or constant inter-arrival gaps
    (request_rate_manager.h)."""

    def __init__(self, *args, distribution="constant", rng_seed=0, **kwargs):
        super().__init__(*args, **kwargs)
        self.distribution = distribution
        self._rng = np.random.default_rng(rng_seed)
        self._schedule_lock = threading.Lock()
        self._next_slot = 0
        self._gaps_ns = []
        self._t0 = None
        self._rate = None

    def _make_schedule(self, rate, horizon=100000):
        mean = 1e9 / rate
        if self.distribution == "poisson":
            return self._rng.exponential(mean, horizon).astype(np.int64)
        return np.full(horizon, int(mean), np.int64)

    def change_request_rate(self, rate, num_threads=None):
        self.stop_workers()
        self._residual = []  # see change_concurrency_level
        self._rate = rate
        self._gaps_ns = np.cumsum(self._make_schedule(rate))
        self._t0 = time.monotonic_ns()
        self._next_slot = 0
        n = num_threads or min(self.max_threads, max(2, int(rate // 4) or 1))
        for slot in range(n):
            self._spawn(self._worker_loop, slot)

    def _extend_schedule(self):
        """Append another horizon chunk so long levels never run dry."""
        more = np.cumsum(self._make_schedule(self._rate)) + int(
            self._gaps_ns[-1]
        )
        self._gaps_ns = np.concatenate([self._gaps_ns, more])

    def _claim_slot(self):
        with self._schedule_lock:
            slot = self._next_slot
            self._next_slot += 1
            if slot >= len(self._gaps_ns):
                if getattr(self, "_rate", None) is None:
                    return None, False  # finite custom schedule exhausted
                self._extend_schedule()
        target_ns = self._t0 + int(self._gaps_ns[slot])
        now = time.monotonic_ns()
        delayed = False
        if now < target_ns:
            time.sleep((target_ns - now) / 1e9)
        elif now - target_ns > 2_000_000:  # >2ms behind schedule
            delayed = True
        return slot, delayed

    def _worker_loop(self, ctx, stop):
        while not stop.is_set():
            slot, delayed = self._claim_slot()
            if slot is None:
                stop.set()  # finite schedule done: a clean stop, not a crash
                return
            ctx.send(delayed=delayed)
            self.count_sent()


class CustomLoadManager(RequestRateManager):
    """Replay user-provided inter-request intervals (custom_load_manager.h)."""

    def __init__(self, *args, intervals_file=None, intervals_ns=None, **kwargs):
        super().__init__(*args, **kwargs)
        if intervals_ns is None:
            if intervals_file is None:
                raise InferenceServerException(
                    "custom load needs --request-intervals file"
                )
            with open(intervals_file) as f:
                intervals_ns = [int(line.strip()) for line in f if line.strip()]
        if not intervals_ns:
            raise InferenceServerException("empty request-intervals data")
        self._intervals = np.asarray(intervals_ns, np.int64)

    def start(self, num_threads=2, repeats=1000):
        self.stop_workers()
        self._residual = []  # see change_concurrency_level
        self._rate = None  # finite replay: no auto-extension
        gaps = np.tile(self._intervals, repeats)
        self._gaps_ns = np.cumsum(gaps)
        self._t0 = time.monotonic_ns()
        self._next_slot = 0
        for slot in range(num_threads):
            self._spawn(self._worker_loop, slot)
