"""Multi-process load generation: worker processes driving one server.

The reference's perf_analyzer is a native multi-threaded binary (reference
src/c++/perf_analyzer/perf_analyzer.cc:56-424, concurrency_worker.cc); a
single-process Python harness shares its GIL between load workers — and, for
an in-process server, with the server itself — so at high concurrency the
measurement instrument becomes the bottleneck.  This module is the
GIL-sidestep: K worker processes, each a full interpreter running its own
``ConcurrencyManager`` slice against the server's real sockets, coordinated
over pipes and merged into one drain-corrected measurement
(``profiler.profile_completion`` semantics).

TPU-shm loads use **region-by-name referencing**: the coordinator (which
owns jax/device access) creates and registers the HBM regions; workers build
requests that reference those regions by name and never initialize a device
backend — exactly how a fleet of remote clients would drive a TPU serving
host.  Linux CLOCK_MONOTONIC is system-wide, so worker-reported window
timestamps merge directly.
"""

import multiprocessing
import time

import numpy as np

from client_tpu.utils import InferenceServerException


class ShapeOnlyLoader:
    """Minimal DataLoader stand-in for preregistered-region workers: knows
    only the (stream, step) topology; carries no tensor data."""

    def __init__(self, num_streams=1, steps_per_stream=(1,)):
        self.num_streams = num_streams
        self._steps = list(steps_per_stream)

    def num_steps(self, stream_id):
        return self._steps[stream_id]

    def get_expected_outputs(self, stream_id, step_id):
        return {}


class PreRegisteredShmInferDataManager:
    """InferData built from region *names* registered by someone else.

    ``input_specs``: {(stream, step): [(name, shape, datatype, region_name,
    nbytes), ...]}; ``output_specs``: [(name, region_name, nbytes)] (empty
    region_name = plain requested output)."""

    completion_sync = False

    def __init__(self, backend, input_specs, output_specs):
        self._backend = backend
        self._input_specs = input_specs
        self._output_specs = output_specs
        self._cache = {}

    def init(self):
        InferInput = self._backend.infer_input_cls
        Requested = self._backend.requested_output_cls
        for (s, t), tensors in self._input_specs.items():
            inputs = []
            for name, shape, datatype, region, nbytes in tensors:
                inp = InferInput(name, list(shape), datatype)
                inp.set_shared_memory(region, nbytes)
                inputs.append(inp)
            outputs = []
            for name, region, nbytes in self._output_specs:
                out = Requested(name)
                if region:
                    out.set_shared_memory(region, nbytes)
                outputs.append(out)
            from client_tpu.perf.infer_data import InferData

            self._cache[(s, t)] = InferData(inputs, outputs)

    def get_infer_data(self, stream_id, step_id):
        return self._cache[(stream_id, step_id)]

    def cleanup(self):
        pass


def export_region_specs(data_manager, inputs_meta, loader):
    """(input_specs, output_specs) for PreRegisteredShmInferDataManager from
    a live shm data manager (its regions stay registered with the server)."""
    metas = {m["name"]: m for m in inputs_meta}
    input_specs = {}
    for s in range(loader.num_streams):
        for t in range(loader.num_steps(s)):
            tensors = []
            for name, meta in metas.items():
                entry = data_manager._regions.get((s, t, name))
                if entry is None:
                    continue
                region, nbytes = entry
                td = loader.get_input_data(s, t).get(name)
                shape = list(td.array.shape) if td is not None else meta["shape"]
                tensors.append((name, shape, meta["datatype"], region, nbytes))
            input_specs[(s, t)] = tensors
    output_specs = [
        (name,) + data_manager._out_regions.get(name, ("", 0))
        for name in [m["name"] for m in getattr(data_manager, "_outputs_meta", [])]
    ]
    return input_specs, output_specs


def _worker_main(conn, url, model_name, concurrency, warmup_s, window_s, spec):
    """One load process: build the object graph, wait for 'go', run the
    window, report records.  Never touches a device backend."""
    try:
        from client_tpu.perf import (
            BackendKind,
            ClientBackendFactory,
            ConcurrencyManager,
            DataLoader,
        )
        from client_tpu.perf.infer_data import InferDataManager

        def factory():
            return ClientBackendFactory.create(BackendKind.TRITON_GRPC, url=url)

        if spec["mode"] == "shm_ref":
            loader = ShapeOnlyLoader(
                spec["num_streams"], spec["steps_per_stream"]
            )
            manager_backend = factory()
            data_manager = PreRegisteredShmInferDataManager(
                manager_backend, spec["input_specs"], spec["output_specs"]
            )
        else:  # wire: generate tensor data locally from server metadata
            manager_backend = factory()
            meta = manager_backend.model_metadata(model_name, "")
            inputs_meta = [dict(m) for m in meta["inputs"]]
            for m in inputs_meta:
                dims = [int(d) for d in m["shape"]]
                if dims and dims[0] == -1:
                    dims[0] = 1
                m["shape"] = dims
            outputs_meta = [dict(m) for m in meta["outputs"]]
            loader = DataLoader(inputs_meta, batch_size=1)
            loader.generate_data()
            data_manager = InferDataManager(
                manager_backend, loader, inputs_meta, outputs_meta
            )
        data_manager.init()
        manager = ConcurrencyManager(
            backend_factory=factory,
            data_loader=loader,
            data_manager=data_manager,
            model_name=model_name,
            max_threads=concurrency,
        )
        conn.send({"ready": True})
        assert conn.recv() == "go"
        manager.change_concurrency_level(concurrency)
        time.sleep(warmup_s)
        manager.swap_timestamps()
        manager.get_and_reset_num_sent()
        t0 = time.monotonic_ns()
        time.sleep(window_s)
        manager.stop_workers()
        t1 = time.monotonic_ns()
        records = manager.swap_timestamps()
        sent = manager.get_and_reset_num_sent()
        ok = [r for r in records if r.ok]
        conn.send(
            {
                "ok": len(ok),
                "errors": len(records) - len(ok),
                "sent": sent,
                "t0": t0,
                "t1": t1,
                "latencies_ns": [r.end_ns - r.start_ns for r in ok],
            }
        )
        manager.cleanup()
        try:
            manager_backend.close()
        except Exception:
            pass
    except Exception as e:  # noqa: BLE001 - reported to the coordinator
        try:
            conn.send({"error": f"{type(e).__name__}: {e}"})
        except Exception:
            pass
    finally:
        conn.close()


class ProcPoolResult:
    def __init__(self):
        self.throughput = 0.0
        self.completed_requests = 0
        self.error_count = 0
        self.send_rate = 0.0
        self.percentiles_us = {}
        self.latency_avg_us = 0.0
        self.window_s = 0.0
        self.processes = 0
        self.concurrency = 0


def run_completion_multiproc(url, model_name, *, processes, concurrency,
                             window_s=8.0, warmup_s=2.0, spec=None,
                             sync_outputs=None, start_timeout_s=180.0,
                             on_go=None):
    """Drain-corrected completion measurement across worker processes.

    *concurrency* is the TOTAL outstanding-request count, split evenly.
    *sync_outputs* (coordinator-side) forces D2H visibility of every output
    region before the clock closes — same semantics as
    InferenceProfiler.profile_completion."""
    spec = spec or {"mode": "wire"}
    processes = max(int(processes), 1)
    per = max(concurrency // processes, 1)
    ctx = multiprocessing.get_context("spawn")
    workers = []
    try:
        for _ in range(processes):
            parent_conn, child_conn = ctx.Pipe()
            p = ctx.Process(
                target=_worker_main,
                args=(child_conn, url, model_name, per, warmup_s, window_s,
                      spec),
                daemon=True,
            )
            p.start()
            child_conn.close()
            workers.append((p, parent_conn))
        deadline = time.monotonic() + start_timeout_s
        for p, conn in workers:
            if not conn.poll(max(deadline - time.monotonic(), 0.1)):
                raise InferenceServerException(
                    "load worker process failed to initialize in time"
                )
            msg = conn.recv()
            if "error" in msg:
                raise InferenceServerException(
                    f"load worker failed: {msg['error']}"
                )
        for _, conn in workers:
            conn.send("go")
        if on_go is not None:
            on_go()  # e.g. snapshot server busy counters at window start
        results = []
        wait_s = warmup_s + window_s + 60
        for p, conn in workers:
            if not conn.poll(wait_s):
                raise InferenceServerException(
                    "load worker process did not report results"
                )
            msg = conn.recv()
            if "error" in msg:
                raise InferenceServerException(
                    f"load worker failed: {msg['error']}"
                )
            results.append(msg)
        if sync_outputs is not None:
            sync_outputs()  # drain: only completed device work counts
        t_close = time.monotonic_ns()
        out = ProcPoolResult()
        out.processes = processes
        out.concurrency = per * processes
        t0 = min(r["t0"] for r in results)
        elapsed = (t_close - t0) / 1e9
        out.window_s = elapsed
        out.completed_requests = sum(r["ok"] for r in results)
        out.error_count = sum(r["errors"] for r in results)
        out.throughput = out.completed_requests / elapsed if elapsed else 0.0
        out.send_rate = sum(r["sent"] for r in results) / elapsed if elapsed else 0.0
        lat = np.concatenate(
            [np.asarray(r["latencies_ns"], np.int64) for r in results]
        ) if any(r["latencies_ns"] for r in results) else np.array([], np.int64)
        if lat.size:
            out.latency_avg_us = float(lat.mean()) / 1e3
            for p_ in (50, 90, 95, 99):
                out.percentiles_us[p_] = float(np.percentile(lat, p_)) / 1e3
        return out
    finally:
        for p, conn in workers:
            try:
                conn.close()
            except Exception:
                pass
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
