"""Result reporting: stdout summary + CSV and JSON export.

Parity with the reference ReportWriter (reference
src/c++/perf_analyzer/report_writer.cc:39-246): a per-level stdout block in
the perf_analyzer format and a CSV with one row per load level (verbose adds
send rate, delayed/error counts and server-side breakdown columns).  The
JSON export carries the FULL per-sweep-point record (every latency
percentile, per-endpoint/per-tenant splits, server + ensemble stats
deltas, tpu_metrics aggregates) — the machine-readable companion the flat
CSV column set cannot hold.
"""

import csv
import json


def print_summary(results, percentile=None):
    """``percentile`` marks which latency governed the stability check."""
    for s in results:
        label = s.level_label.replace("_", " ").title()
        print(f"{label}: {s.level_value}")
        if not s.stable:
            print("  WARNING: measurements did not stabilize")
        print(
            f"  Client: request count: {s.completed_requests}, "
            f"throughput: {s.throughput:.1f} infer/sec, "
            f"send rate: {s.send_rate:.1f} req/sec"
        )
        if s.error_count:
            print(f"    failed requests: {s.error_count}")
        if s.delayed_count:
            print(f"    delayed requests: {s.delayed_count}")
        print(f"    avg latency: {s.latency_avg_us:.0f} usec")
        for p in sorted(s.percentiles_us):
            governed = " (stability metric)" if p == percentile else ""
            print(
                f"    p{p} latency: {s.percentiles_us[p]:.0f} usec{governed}"
            )
        for endpoint, ep in sorted(s.per_endpoint.items()):
            failed = f", {ep['errors']} failed" if ep["errors"] else ""
            print(
                f"    endpoint {endpoint}: {ep['count']} ok, "
                f"{ep['throughput']:.1f} infer/sec, "
                f"avg {ep['avg_us']:.0f} usec, "
                f"p99 {ep['p99_us']:.0f} usec{failed}"
            )
        for tenant, tp in sorted(s.per_tenant.items()):
            failed = f", {tp['errors']} failed" if tp["errors"] else ""
            print(
                f"    tenant {tenant or '(default)'}: {tp['count']} ok, "
                f"{tp['throughput']:.1f} infer/sec, "
                f"avg {tp['avg_us']:.0f} usec, "
                f"p99 {tp['p99_us']:.0f} usec{failed}"
            )
        for gauge, agg in sorted(s.tpu_metrics.items()):
            print(
                f"    {gauge}: avg {agg['avg']:.0f}, max {agg['max']:.0f}"
            )
        if s.lm_prefix:
            print(
                f"    prefix cache: {s.lm_prefix['prefix_hit_pct']:.1f}% "
                "block hit rate, "
                f"{s.lm_prefix['prefill_tokens_saved_pct']:.1f}% prefill "
                "tokens saved"
            )
        if s.lm_spec:
            print(
                f"    speculative: {s.lm_spec['spec_acceptance_pct']:.1f}% "
                "draft acceptance, "
                f"{s.lm_spec['spec_tokens_per_sec']:.1f} LM tokens/s"
            )
        if s.overhead_pct:
            print(f"    harness overhead: {s.overhead_pct:.1f}% of slot time")
        if s.server_stats:
            srv = s.server_stats
            cnt = max(srv.get("success_count", 0), 1)
            parts = []
            for phase in ("queue", "compute_input", "compute_infer",
                          "compute_output"):
                ns = srv.get(f"{phase}_ns", 0)
                parts.append(f"{phase} {ns / cnt / 1e3:.0f}")
            print(f"  Server: avg usec/request: {', '.join(parts)}")
            hits = srv.get("cache_hit_count", 0)
            if hits or srv.get("cache_miss_count", 0):
                served = hits + srv.get("cache_miss_count", 0)
                pct = 100.0 * hits / served if served else 0.0
                print(
                    f"    response cache: {hits} hits / {served} lookups "
                    f"({pct:.1f}%), avg hit "
                    f"{srv.get('cache_hit_ns', 0) / max(hits, 1) / 1e3:.0f} "
                    "usec"
                )
        for name, counters in sorted(s.ensemble_stats.items()):
            # full per-stage phase split (mirrors the reference's ensemble
            # recursion in ReportWriter): each composing model's queue and
            # compute breakdown under its own name, so a pipeline's slow
            # stage is visible straight from the sweep output
            cnt = max(counters.get("success_count", 0), 1)
            parts = []
            for phase in ("queue", "compute_input", "compute_infer",
                          "compute_output"):
                ns = counters.get(f"{phase}_ns", 0)
                parts.append(f"{phase} {ns / cnt / 1e3:.0f}")
            print(
                f"  Composing model {name}: {counters.get('success_count', 0)}"
                f" exec, avg usec/request: {', '.join(parts)}"
            )
        print()
    if results:
        best = max(results, key=lambda s: s.throughput)
        print(
            f"Best: {best.level_label}={best.level_value} -> "
            f"{best.throughput:.1f} infer/sec, "
            f"avg latency {best.latency_avg_us:.0f} usec"
        )


def write_csv(path, results, verbose=False):
    """CSV export; column set follows report_writer.cc, plus one avg/max
    column pair per collected tpu_metrics gauge (the reference appends GPU
    metric columns the same way)."""
    fields = [
        "Level", "Inferences/Second", "Client Send Rate",
        "Avg latency", "p50 latency", "p90 latency", "p95 latency",
        "p99 latency", "Request Count", "Failed Count", "Delayed Count",
        "Stable",
    ]
    if verbose:
        fields += [
            "Server Queue", "Server Compute Input", "Server Compute Infer",
            "Server Compute Output", "Server Cache Hits",
        ]
    # --prefix-share sweeps: the per-level KV prefix-cache outcome
    has_prefix = any(s.lm_prefix for s in results)
    if has_prefix:
        fields += ["Prefix Hit %", "Prefill Tokens Saved %"]
    # --speculative sweeps: the per-level draft/verify outcome
    has_spec = any(s.lm_spec for s in results)
    if has_spec:
        fields += ["Spec Acceptance %", "LM Tokens/Second"]
    # ensemble targets: one queue/compute column pair per composing model
    # (the reference appends per-composing columns the same way)
    composing = sorted({n for s in results for n in s.ensemble_stats})
    for name in composing:
        fields += [f"Ensemble {name} Queue", f"Ensemble {name} Compute"]
    gauges = sorted({g for s in results for g in s.tpu_metrics})
    for gauge in gauges:
        fields += [f"{gauge} (avg)", f"{gauge} (max)"]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(fields)
        for s in results:
            row = [
                s.level_value,
                f"{s.throughput:.2f}",
                f"{s.send_rate:.2f}",
                f"{s.latency_avg_us:.0f}",
                f"{s.percentiles_us.get(50, 0):.0f}",
                f"{s.percentiles_us.get(90, 0):.0f}",
                f"{s.percentiles_us.get(95, 0):.0f}",
                f"{s.percentiles_us.get(99, 0):.0f}",
                s.completed_requests,
                s.error_count,
                s.delayed_count,
                int(s.stable),
            ]
            if verbose:
                srv = s.server_stats
                cnt = max(srv.get("success_count", 0), 1)
                row += [
                    f"{srv.get('queue_ns', 0) / cnt / 1e3:.0f}",
                    f"{srv.get('compute_input_ns', 0) / cnt / 1e3:.0f}",
                    f"{srv.get('compute_infer_ns', 0) / cnt / 1e3:.0f}",
                    f"{srv.get('compute_output_ns', 0) / cnt / 1e3:.0f}",
                    str(srv.get("cache_hit_count", 0)),
                ]
            if has_prefix:
                row += (
                    [f"{s.lm_prefix['prefix_hit_pct']:.2f}",
                     f"{s.lm_prefix['prefill_tokens_saved_pct']:.2f}"]
                    if s.lm_prefix else ["", ""]
                )
            if has_spec:
                row += (
                    [f"{s.lm_spec['spec_acceptance_pct']:.2f}",
                     f"{s.lm_spec['spec_tokens_per_sec']:.1f}"]
                    if s.lm_spec else ["", ""]
                )
            for name in composing:
                counters = s.ensemble_stats.get(name)
                if not counters:
                    row += ["", ""]
                    continue
                cnt = max(counters.get("success_count", 0), 1)
                row += [
                    f"{counters.get('queue_ns', 0) / cnt / 1e3:.0f}",
                    f"{counters.get('compute_infer_ns', 0) / cnt / 1e3:.0f}",
                ]
            for gauge in gauges:
                agg = s.tpu_metrics.get(gauge)
                row += ([f"{agg['avg']:.1f}", f"{agg['max']:.1f}"]
                        if agg else ["", ""])
            w.writerow(row)


def status_record(s):
    """One sweep point as a JSON-ready dict (every field PerfStatus
    carries; percentile keys stringified for stable JSON)."""
    return {
        "level_label": s.level_label,
        "level_value": s.level_value,
        "throughput_infer_per_sec": s.throughput,
        "send_rate_req_per_sec": s.send_rate,
        "latency_avg_us": s.latency_avg_us,
        "percentiles_us": {
            str(p): v for p, v in sorted(s.percentiles_us.items())
        },
        "completed_requests": s.completed_requests,
        "error_count": s.error_count,
        "delayed_count": s.delayed_count,
        "stable": bool(s.stable),
        "client_window_s": s.client_window_s,
        "overhead_pct": s.overhead_pct,
        "per_endpoint": s.per_endpoint,
        "per_tenant": s.per_tenant,
        "tpu_metrics": s.tpu_metrics,
        "server_stats": s.server_stats,
        "ensemble_stats": s.ensemble_stats,
        "lm_prefix": s.lm_prefix,
        "lm_spec": s.lm_spec,
    }


def write_json(path, results, extra=None):
    """Per-sweep-point JSON export: ``{"results": [record, ...]}`` plus
    any ``extra`` top-level keys (e.g. the SLO search's best level)."""
    doc = dict(extra or {})
    doc["results"] = [status_record(s) for s in results]
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
