"""Model metadata/config normalization for the perf harness.

Parity with the reference ModelParser (reference
src/c++/perf_analyzer/model_parser.h:59-193): one object that fuses the
metadata and config endpoints into the normalized facts the load engine
needs — resolved tensor shapes, max_batch_size, scheduler kind, decoupled
transaction policy, and the (transitive) composing models of an ensemble —
so the CLI and managers never poke at raw JSON again.
"""

from client_tpu.utils import InferenceServerException


class SchedulerType:
    NONE = "none"
    DYNAMIC = "dynamic"
    SEQUENCE = "sequence"
    ENSEMBLE = "ensemble"
    ENSEMBLE_SEQUENCE = "ensemble_sequence"


class ModelParser:
    """Normalized view over one model's metadata + config."""

    def __init__(self, model_name, model_version=""):
        self.model_name = model_name
        self.model_version = model_version
        self.inputs = []   # [{"name","datatype","shape"(int list)}]
        self.outputs = []
        self.max_batch_size = 0
        self.scheduler_type = SchedulerType.NONE
        self.is_decoupled = False
        self.composing_models = []  # transitive, ensemble order
        self.response_cache_enabled = False

    @classmethod
    def create(cls, backend, model_name, model_version="", batch_size=1):
        """Fetch + normalize (reference ModelParser::InitTriton)."""
        parser = cls(model_name, model_version)
        meta = backend.model_metadata(model_name, model_version)
        try:
            config = backend.model_config(model_name, model_version) or {}
        except (InferenceServerException, NotImplementedError):
            config = {}
        parser._init_tensors(meta, batch_size)
        parser._init_config(config)
        parser._init_composing(backend, config)
        return parser

    def _init_tensors(self, meta, batch_size):
        def norm(entries):
            out = []
            for m in entries:
                # protobuf-JSON renders int64 dims as strings; a dynamic
                # leading (batch) dim resolves to the requested batch size
                dims = [int(d) for d in m.get("shape", [])]
                if dims and dims[0] == -1:
                    dims[0] = batch_size
                out.append({
                    "name": m["name"],
                    "datatype": m.get("datatype", "FP32"),
                    "shape": dims,
                })
            return out

        self.inputs = norm(meta.get("inputs", []))
        self.outputs = norm(meta.get("outputs", []))

    def _init_config(self, config):
        self.max_batch_size = int(config.get("max_batch_size", 0) or 0)
        policy = config.get("model_transaction_policy", {}) or {}
        self.is_decoupled = bool(policy.get("decoupled", False))
        self.response_cache_enabled = bool(
            (config.get("response_cache") or {}).get("enable", False)
        )
        has_sequence = "sequence_batching" in config
        has_dynamic = "dynamic_batching" in config
        has_ensemble = bool(
            (config.get("ensemble_scheduling") or {}).get("step")
        )
        if has_ensemble:
            self.scheduler_type = (
                SchedulerType.ENSEMBLE_SEQUENCE
                if has_sequence
                else SchedulerType.ENSEMBLE
            )
        elif has_sequence:
            self.scheduler_type = SchedulerType.SEQUENCE
        elif has_dynamic:
            self.scheduler_type = SchedulerType.DYNAMIC
        else:
            self.scheduler_type = SchedulerType.NONE

    def _init_composing(self, backend, config, seen=None):
        seen = seen if seen is not None else {self.model_name}
        steps = (config.get("ensemble_scheduling") or {}).get("step") or []
        for step in steps:
            name = step.get("model_name")
            if not name or name in seen:
                continue
            seen.add(name)
            self.composing_models.append(name)
            try:
                sub_cfg = backend.model_config(name) or {}
            except (InferenceServerException, NotImplementedError):
                continue
            # nested ensembles recurse (reference GetEnsembleSchedulerType)
            self._init_composing(backend, sub_cfg, seen)
            sub_policy = sub_cfg.get("model_transaction_policy", {}) or {}
            if sub_policy.get("decoupled"):
                self.is_decoupled = True

    def requires_sequence_flags(self):
        return self.scheduler_type in (
            SchedulerType.SEQUENCE, SchedulerType.ENSEMBLE_SEQUENCE
        )
