"""perf CLI — the perf_analyzer command-line surface.

Option names follow the reference CLI (reference
src/c++/perf_analyzer/command_line_parser.h:44-160) where the concept
carries over; TPU-specific additions: ``--shared-memory tpu`` stages inputs
in TPU HBM, ``--hermetic MODEL`` benchmarks the in-process server without
sockets (the TRITON_C_API analog).
"""

import argparse
import sys

from client_tpu.perf import (
    BackendKind,
    ClientBackendFactory,
    ConcurrencyManager,
    CustomLoadManager,
    DataLoader,
    InferenceProfiler,
    RequestRateManager,
    SequenceManager,
    create_infer_data_manager,
    print_summary,
    write_csv,
    write_json,
)
from client_tpu.perf.model_parser import ModelParser
from client_tpu.utils import InferenceServerException


def _parse_tenants(spec):
    """'gold:3,bronze:1' -> ['gold','gold','gold','bronze']: the slot
    assignment list worker i indexes with i % len (a bare name counts as
    weight 1).  Interleaving is by expansion order, which is fine — slots
    are homogeneous."""
    if not spec:
        return []
    slots = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition(":")
        try:
            count = int(weight) if weight else 1
        except ValueError:
            raise SystemExit(
                f"error: bad --tenants entry {part!r} (want name[:weight])"
            ) from None
        if count < 1:
            raise SystemExit(
                f"error: --tenants weight must be >= 1 in {part!r}"
            )
        slots.extend([name] * count)
    return slots


def _parse_range(text, cast):
    """start[:end[:step]] (reference concurrency-range format)."""
    parts = text.split(":")
    start = cast(parts[0])
    end = cast(parts[1]) if len(parts) > 1 else start
    step = cast(parts[2]) if len(parts) > 2 else cast(1)
    return start, end, step


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m client_tpu.perf",
        description="TPU-native perf_analyzer: load generation + measurement",
    )
    p.add_argument("-m", "--model-name", required=True)
    p.add_argument("-x", "--model-version", default="")
    p.add_argument("-u", "--url", default="localhost:8001",
                   help="server address; a comma-separated list fans the "
                        "load out across replicas (per-endpoint split in "
                        "the summary)")
    p.add_argument("-i", "--protocol", choices=["grpc", "http"], default="grpc")
    p.add_argument("-a", "--async", dest="async_mode", action="store_true",
                   help="async concurrency slots on one event loop over "
                        "grpc.aio (reference -a; stateless gRPC only)")
    p.add_argument("--native-loadgen", action="store_true",
                   help="generate load with the native C++ engine "
                        "(build/cpp/perf_worker: async InferContexts on one "
                        "connection, no GIL in the instrument); concurrency "
                        "mode over socket gRPC, wire or TPU-shm inputs")
    p.add_argument("--service-kind",
                   choices=["triton", "torchserve", "tfserve",
                            "tfserve_rest"],
                   default="triton",
                   help="target service protocol family (reference "
                        "--service-kind; non-KServe kinds declare the input "
                        "tensor via --shape)")
    p.add_argument("--hermetic", action="store_true",
                   help="benchmark the in-process server (no sockets); with "
                        "--service-kind torchserve/tfserve spins the "
                        "matching in-process fake endpoint")
    p.add_argument("--hermetic-models", default="builtin",
                   help="model sets for --hermetic: builtin,jax,language")
    p.add_argument("-b", "--batch-size", type=int, default=1)
    p.add_argument("--concurrency-range", default=None,
                   help="start[:end[:step]]")
    p.add_argument("--request-rate-range", default=None,
                   help="start[:end[:step]] in req/sec")
    p.add_argument("--request-intervals", default=None,
                   help="file of inter-request intervals (ns per line)")
    p.add_argument("--request-distribution", choices=["constant", "poisson"],
                   default="constant")
    p.add_argument("--measurement-interval", type=int, default=2000,
                   help="window length in msec (-p)")
    p.add_argument("--measurement-mode",
                   choices=["time_windows", "count_windows"],
                   default="time_windows",
                   help="close windows on elapsed time or on completed "
                        "request count (reference --measurement-mode)")
    p.add_argument("--measurement-request-count", type=int, default=50,
                   help="requests per window for count_windows mode")
    p.add_argument("--max-trials", type=int, default=10)
    p.add_argument("-s", "--stability-percentage", type=float, default=10.0)
    p.add_argument("--percentile", type=int, default=None,
                   help="use this latency percentile for stability checks")
    p.add_argument("-l", "--latency-threshold", type=float, default=0,
                   help="stop the sweep past this avg latency (msec)")
    p.add_argument("--binary-search", action="store_true")
    p.add_argument("--max-threads", type=int, default=16)
    p.add_argument("--shared-memory", choices=["none", "system", "tpu"],
                   default="none")
    p.add_argument("--output-shared-memory-size", type=int, default=0)
    p.add_argument("--tpu-device-id", type=int, default=0)
    p.add_argument("--tpu-shm-sync", action="store_true",
                   help="record completion latency (forced D2H per request) "
                        "instead of dispatch-ack latency for TPU shm outputs")
    p.add_argument("--input-data", default=None,
                   help="'random', 'zero', a JSON file, or a directory")
    p.add_argument("--shape", action="append", default=[],
                   help="NAME:d1,d2,... override for dynamic dims")
    p.add_argument("--string-length", type=int, default=16)
    p.add_argument("--prefix-share", type=float, default=None,
                   help="LM workload knob: generate prompts whose leading "
                        "FRAC of tokens comes from a small shared prefix "
                        "pool (see --prefix-pool), so the KV prefix "
                        "cache's prefill savings are measurable; with "
                        "--hermetic the summary/CSV/JSON gain per-sweep "
                        "prefix_hit_pct + prefill_tokens_saved_pct from "
                        "the engine's counters")
    p.add_argument("--prefix-pool", type=int, default=4,
                   help="number of distinct shared prefixes --prefix-share "
                        "draws from (smaller pool = hotter prefixes)")
    p.add_argument("--prefix-prompts", type=int, default=16,
                   help="distinct prompts generated for --prefix-share "
                        "(workers rotate over them)")
    p.add_argument("--speculative", type=int, default=None, metavar="K",
                   help="LM engine knob (requires --hermetic): enable "
                        "speculative decoding with up to K draft tokens "
                        "per verify tick on the batched LM engines; the "
                        "summary/CSV/JSON gain per-sweep "
                        "spec_acceptance_pct + spec tokens/s from the "
                        "engine's counters")
    p.add_argument("--drafter", choices=["ngram", "bigram"],
                   default="ngram",
                   help="drafter for --speculative: 'ngram' "
                        "(prompt-lookup) or 'bigram' (static greedy-"
                        "bigram table seeded from the prompt)")
    p.add_argument("--tenants", default=None,
                   help="tenant mix for the worker slots: "
                        "'gold:3,bronze:1' assigns slots to tenants "
                        "proportionally to the weights (a bare name means "
                        "weight 1); requests carry x-tenant-id and the "
                        "summary adds a per-tenant latency split — the "
                        "noisy-neighbor isolation readout against a QoS-"
                        "enabled server")
    p.add_argument("--hermetic-cache-entries", type=int, default=0,
                   help="with --hermetic: enable the in-process engine's "
                        "response cache (N LRU entries) + coalescing, so "
                        "cache-hit rates show in the summary")
    p.add_argument("--sequence", action="store_true",
                   help="stateful sequence workload")
    p.add_argument("--sequence-length", type=int, default=20)
    p.add_argument("--sequence-length-variation", type=float, default=0.0)
    p.add_argument("--start-sequence-id", type=int, default=1)
    p.add_argument("--sequence-id-range", type=int, default=2**32 - 1)
    p.add_argument("--churn-soak", type=float, default=None,
                   metavar="SECONDS",
                   help="with a --url replica list: soak the replica pool "
                        "under membership churn — every SECONDS a rotating "
                        "replica is retired from the pool through the "
                        "discovery layer and re-added one tick later "
                        "(retire/evict/re-add paths exercised under load; "
                        "the last healthy endpoint is never dropped)")
    p.add_argument("-f", "--filename", default=None, help="CSV output path")
    p.add_argument("--json-export", default=None,
                   help="per-sweep-point JSON report path (the full "
                        "record CSV columns cannot hold: all percentiles, "
                        "per-endpoint/tenant splits, server stats deltas)")
    p.add_argument("--collect-metrics", action="store_true",
                   help="scrape the server /metrics during measurement")
    p.add_argument("--metrics-url", default=None,
                   help="metrics endpoint (default: http://<url>/metrics)")
    p.add_argument("--metrics-interval", type=float, default=1000.0,
                   help="scrape interval in msec")
    p.add_argument("--collect-local-tpu-metrics", action="store_true",
                   help="also sample this host's PJRT device gauges (HBM "
                        "used/total/peak) each scrape — device telemetry "
                        "when the server under test exposes no TPU metrics "
                        "(requires colocation with the chip)")
    p.add_argument("--probe-device-utilization", action="store_true",
                   help="estimate device utilization by timing a tiny probe "
                        "kernel each scrape (queue-delay sampling; trusts "
                        "nothing the server reports; requires colocation "
                        "with the chip) — summarized per window as "
                        "ctpu_probe_utilization_pct in the report/CSV")
    # SSL/TLS (reference command_line_parser.h SSL option block; names match)
    p.add_argument("--ssl-grpc-use-ssl", action="store_true",
                   help="use an SSL-encrypted gRPC channel")
    p.add_argument("--ssl-grpc-root-certifications-file", default=None)
    p.add_argument("--ssl-grpc-private-key-file", default=None)
    p.add_argument("--ssl-grpc-certificate-chain-file", default=None)
    p.add_argument("--ssl-https-verify-peer", type=int, choices=[0, 1],
                   default=1, help="0 disables server-cert verification")
    p.add_argument("--ssl-https-ca-certificates-file", default=None,
                   help="also switches the HTTP client to https://")
    p.add_argument("--ssl-https-client-certificate-file", default=None)
    p.add_argument("--ssl-https-private-key-file", default=None)
    # trace control plane: pushed to the server before profiling (reference
    # command_line_parser.h trace options → TraceSetting RPC)
    p.add_argument("--trace-level", action="append", default=None,
                   choices=["OFF", "TIMESTAMPS", "TENSORS"],
                   help="may repeat; OFF clears")
    p.add_argument("--trace-rate", type=int, default=None,
                   help="trace 1 of every N requests")
    p.add_argument("--trace-count", type=int, default=None,
                   help="stop tracing after N traces (-1 = unlimited)")
    p.add_argument("--log-frequency", type=int, default=None,
                   help="flush the trace log every N traces")
    p.add_argument("--world-size", type=int, default=1,
                   help="number of coordinated perf ranks (MPI-mode analog)")
    p.add_argument("--rank", type=int, default=0)
    p.add_argument("--rendezvous-addr", default="127.0.0.1:29400",
                   help="rank-0 coordinator host:port")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def _run_native_loadgen(args, control, loader, data_manager):
    """Load sweep driven by the native C++ engine (perf_worker): region
    setup and metadata live here (this process owns jax); the measurement
    loop is pure C++.  Sweeps concurrency, request rate
    (--request-rate-range, constant/poisson), or stateful sequences
    (--sequence) — each level runs one worker long enough for the
    stability loop over its per-window records."""
    from client_tpu.perf.infer_data import _ShmInferDataManagerBase
    from client_tpu.perf.native_worker import (
        native_windows_stable,
        run_native_worker,
    )
    from client_tpu.utils import np_to_triton_dtype

    try:
        wire_inputs, shm_inputs, shm_outputs = [], [], []
        step0 = loader.get_input_data(0, 0)
        if isinstance(data_manager, _ShmInferDataManagerBase):
            for name, td in step0.items():
                region, nbytes = data_manager._regions[(0, 0, name)]
                shm_inputs.append((
                    name, np_to_triton_dtype(td.array.dtype),
                    list(td.array.shape), region, nbytes,
                ))
            for name, (region, nbytes) in data_manager._out_regions.items():
                shm_outputs.append((name, region, nbytes))
        else:
            for name, td in step0.items():
                wire_inputs.append((
                    name, np_to_triton_dtype(td.array.dtype),
                    list(td.array.shape),
                ))

        window_s = max(args.measurement_interval / 1e3, 0.5)
        # enough windows for the 3-window stability check without letting
        # default settings balloon a level past ~6 windows
        n_windows = max(3, min(args.max_trials, 6))
        threshold = args.stability_percentage / 100.0

        if args.request_rate_range:
            start, end, step = _parse_range(args.request_rate_range, float)
            # index-based levels: float accumulation (r += step) can skip
            # the final level to rounding (0.1+0.1+0.1 > 0.3)
            n_levels = int(round((end - start) / step)) + 1 if step else 1
            levels = []
            for i in range(max(n_levels, 1)):
                r = start + i * step
                if r > end * (1 + 1e-9):
                    break
                levels.append(("Request rate", r, {
                    "request_rate": r,
                    "distribution": args.request_distribution,
                    "concurrency": args.max_threads,
                }))
        else:
            start, end, step = _parse_range(args.concurrency_range or "1", int)
            label = "Sequences" if args.sequence else "Concurrency"
            levels = []
            c = start
            while c <= end:
                kw = ({"sequences": c, "seq_steps": args.sequence_length,
                       "concurrency": 1}
                      if args.sequence else {"concurrency": c})
                levels.append((label, c, kw))
                c += step

        best = None
        errors = 0
        for label, level, kw in levels:
            report = run_native_worker(
                args.url, args.model_name,
                duration_s=window_s * n_windows, warmup_s=1.0,
                window_interval_s=window_s,
                completion_sync=args.tpu_shm_sync,
                wire_inputs=wire_inputs, shm_inputs=shm_inputs,
                shm_outputs=shm_outputs, **kw,
            )
            errors += report["errors"]
            windows = report.get("windows", [])
            stable = native_windows_stable(windows, threshold)
            if stable:
                tail = windows[-3:]
                report["stable_throughput"] = round(
                    sum(w["throughput"] for w in tail) / 3, 2
                )
            delayed = (f", delayed {report['delayed']}"
                       if report.get("delayed") else "")
            print(
                f"{label}: {level:g}, throughput: "
                f"{report['throughput']:.1f} infer/sec (native), "
                f"p50 {report['p50_us']:.0f} usec, "
                f"p99 {report['p99_us']:.0f} usec, "
                f"errors {report['errors']}{delayed}, "
                f"{'stable' if stable else 'UNSTABLE'} over "
                f"{len(windows)} windows"
            )
            if best is None or report["throughput"] > best[1]["throughput"]:
                best = (level, report)
        if best is not None:
            name = ("rate" if args.request_rate_range
                    else "sequences" if args.sequence else "concurrency")
            print(
                f"Best: {name}={best[0]:g} -> "
                f"{best[1]['throughput']:.1f} infer/sec, "
                f"avg latency {best[1]['avg_us']:.0f} usec"
            )
        return 0 if best is not None and errors == 0 else 1
    finally:
        data_manager.cleanup()
        try:
            control.close()
        except Exception:
            pass


def main(argv=None):
    args = build_parser().parse_args(argv)

    urls = [u.strip() for u in args.url.split(",") if u.strip()]

    shape_overrides = {}
    for item in args.shape:
        name, _, dims = item.partition(":")
        shape_overrides[name] = [int(d) for d in dims.split(",")]

    if args.speculative is not None:
        if args.speculative < 1:
            sys.exit("error: --speculative K must be >= 1")
        if not args.hermetic:
            sys.exit("error: --speculative configures the in-process LM "
                     "engine; add --hermetic")

    engine = None
    fake = None
    backend_kwargs = {}
    if args.service_kind in ("torchserve", "tfserve", "tfserve_rest"):
        kind = {
            "torchserve": BackendKind.TORCHSERVE,
            "tfserve": BackendKind.TFSERVE,  # gRPC PredictionService
            "tfserve_rest": BackendKind.TFSERVE_REST,
        }[args.service_kind]
        # --shape stays tensor-name-keyed: these services declare one input
        # ("data" / "instances" / "input" — the names their backends
        # synthesize)
        tensor = {
            "torchserve": "data",
            "tfserve": "input",
            "tfserve_rest": "instances",
        }[args.service_kind]
        if tensor in shape_overrides:
            backend_kwargs["input_shape"] = shape_overrides[tensor]
        for key in shape_overrides:
            if key != tensor:
                print(
                    f"warning: --shape '{key}' does not match this service "
                    f"kind's input tensor '{tensor}'; ignored",
                    file=sys.stderr,
                )
        if args.hermetic:
            from client_tpu.perf.fake_endpoints import (
                fake_tfserving,
                fake_tfserving_grpc,
                fake_torchserve,
            )

            fake = {
                "torchserve": fake_torchserve,
                "tfserve": fake_tfserving_grpc,
                "tfserve_rest": fake_tfserving,
            }[args.service_kind]([args.model_name]).start()
            args.url = fake.url
    elif args.hermetic:
        from client_tpu.serve import InferenceEngine
        from client_tpu.serve.models import model_sets

        cache = None
        if args.hermetic_cache_entries > 0:
            from client_tpu.serve.frontdoor import ResponseCache

            cache = ResponseCache(max_entries=args.hermetic_cache_entries)
        speculative = None
        if args.speculative is not None:
            speculative = {"k": args.speculative, "drafter": args.drafter}
        engine = InferenceEngine(  # no sockets
            model_sets(args.hermetic_models, speculative=speculative),
            response_cache=cache,
            coalescing=args.hermetic_cache_entries > 0,
        )
        kind = BackendKind.INPROCESS
    else:
        kind = (
            BackendKind.TRITON_GRPC
            if args.protocol == "grpc"
            else BackendKind.TRITON_HTTP
        )

    # Multi-replica fan-out: workers are assigned round-robin across the
    # --url list via an EndpointPool, and the summary reports a
    # per-endpoint throughput/latency split.
    replica_pool = None
    if len(urls) > 1:
        if (args.hermetic or args.native_loadgen or args.async_mode
                or kind not in (BackendKind.TRITON_GRPC,
                                BackendKind.TRITON_HTTP)):
            sys.exit("error: a --url replica list drives the python load "
                     "engine over socket HTTP/gRPC (not --hermetic, "
                     "--native-loadgen, --async, or non-Triton "
                     "--service-kind)")
        if args.shared_memory != "none":
            sys.exit("error: --shared-memory regions are registered on one "
                     "server; they cannot fan out across a --url replica "
                     "list")
        if len(set(urls)) != len(urls):
            sys.exit("error: duplicate endpoint in the --url replica list")
        from client_tpu.balance import EndpointPool

        replica_pool = EndpointPool(urls, policy="round-robin")
        args.url = urls[0]  # control plane: metadata/statistics/trace

    # Churn-soak: drive discovery updates into the live pool while the
    # load runs — membership rotates through the resolver machinery, so
    # probation/retire/evict are exercised exactly as production would.
    churn_loop = None
    if args.churn_soak is not None:
        if replica_pool is None:
            sys.exit("error: --churn-soak needs a --url replica list "
                     "(membership churn over a single endpoint would "
                     "violate the last-healthy safety valve every tick)")
        from client_tpu.balance.discovery import (
            CallableResolver,
            DiscoveryLoop,
        )

        churn_tick = {"n": 0}

        def churn_membership():
            # tick k retires replica k % (n+1); the full-fleet round
            # (k == n) re-admits everyone, so each replica cycles through
            # retire -> evict -> re-add -> probation -> active
            i = churn_tick["n"] % (len(urls) + 1)
            churn_tick["n"] += 1
            if i == len(urls):
                return list(urls)
            return [u for j, u in enumerate(urls) if j != i]

        churn_loop = DiscoveryLoop(
            replica_pool, CallableResolver(churn_membership),
            interval_s=args.churn_soak,
        ).start()
        if args.verbose:
            print(f"churn soak: rotating {len(urls)} replicas every "
                  f"{args.churn_soak:g}s", file=sys.stderr)

    ssl_options = None
    if args.protocol == "grpc" and args.ssl_grpc_use_ssl:
        ssl_options = {
            "use_ssl": True,
            "root_certificates": args.ssl_grpc_root_certifications_file,
            "private_key": args.ssl_grpc_private_key_file,
            "certificate_chain": args.ssl_grpc_certificate_chain_file,
        }
    elif args.protocol == "http" and (
        args.ssl_https_ca_certificates_file
        or args.ssl_https_client_certificate_file
        or not args.ssl_https_verify_peer
    ):
        ssl_options = {
            "use_ssl": True,
            "verify_peer": bool(args.ssl_https_verify_peer),
            "ca_certificates_file": args.ssl_https_ca_certificates_file,
            "client_certificate_file": args.ssl_https_client_certificate_file,
            "private_key_file": args.ssl_https_private_key_file,
        }

    def backend_factory():
        url = (
            replica_pool.pick().url if replica_pool is not None else args.url
        )
        return ClientBackendFactory.create(
            kind, url=url, engine=engine, verbose=False,
            ssl_options=ssl_options, **backend_kwargs
        )

    control = backend_factory()
    try:
        trace_settings = {}
        if args.trace_level is not None:
            trace_settings["trace_level"] = args.trace_level
        if args.trace_rate is not None:
            trace_settings["trace_rate"] = str(args.trace_rate)
        if args.trace_count is not None:
            trace_settings["trace_count"] = str(args.trace_count)
        if args.log_frequency is not None:
            trace_settings["log_frequency"] = str(args.log_frequency)
        if trace_settings:
            control.update_trace_settings(
                model_name=args.model_name, settings=trace_settings
            )
            if args.verbose:
                print(f"trace settings applied: {trace_settings}",
                      file=sys.stderr)
        parser_obj = ModelParser.create(
            control, args.model_name, args.model_version,
            batch_size=args.batch_size,
        )
        inputs_meta = parser_obj.inputs
        outputs_meta = parser_obj.outputs
        if parser_obj.requires_sequence_flags() and not args.sequence:
            print(
                f"note: model '{args.model_name}' uses the "
                f"{parser_obj.scheduler_type} scheduler; consider --sequence",
                file=sys.stderr,
            )

        loader = DataLoader(
            inputs_meta, batch_size=args.batch_size,
            shape_overrides=shape_overrides,
        )
        if args.prefix_share is not None:
            if args.input_data not in (None, "random"):
                sys.exit("error: --prefix-share generates its own prompt "
                         "workload; drop --input-data")
            if args.native_loadgen:
                sys.exit("error: --prefix-share rotates a prompt set; the "
                         "native engine repeats one fixed request")
            loader.generate_prefix_share(
                args.prefix_share, num_prompts=args.prefix_prompts,
                shared_pool=args.prefix_pool,
            )
        elif args.input_data in (None, "random"):
            loader.generate_data(string_length=args.string_length)
        elif args.input_data == "zero":
            loader.generate_data(zero_data=True,
                                 string_length=args.string_length)
        elif args.input_data.endswith(".json"):
            loader.read_data_from_json(args.input_data)
        else:
            loader.read_data_from_dir(args.input_data)

        data_manager = create_infer_data_manager(
            control, loader, inputs_meta, outputs_meta,
            shared_memory=args.shared_memory,
            output_shm_byte_size=args.output_shared_memory_size,
            device_id=args.tpu_device_id,
            tpu_completion_sync=args.tpu_shm_sync,
        )
        data_manager.init()

        sequences = None
        if args.sequence:
            sequences = SequenceManager(
                start_sequence_id=args.start_sequence_id,
                sequence_id_range=args.sequence_id_range,
                sequence_length=args.sequence_length,
                sequence_length_variation=args.sequence_length_variation,
                sequence_length_specified=True,
                num_streams=loader.num_streams,
            )

        tenant_slots = _parse_tenants(args.tenants)
        if tenant_slots and (args.async_mode or args.native_loadgen):
            sys.exit("error: --tenants drives the thread-per-slot python "
                     "load engine (not --async / --native-loadgen)")
        common = dict(
            backend_factory=backend_factory,
            data_loader=loader,
            data_manager=data_manager,
            model_name=args.model_name,
            model_version=args.model_version,
            sequence_manager=sequences,
            max_threads=args.max_threads,
            tenants=tenant_slots,
        )
        latency_limit_us = args.latency_threshold * 1e3 or None

        if args.async_mode and (args.request_intervals
                                or args.request_rate_range):
            sys.exit("error: --async applies to concurrency mode only "
                     "(request-rate/interval schedules use worker threads)")
        if args.native_loadgen:
            if (args.hermetic or kind != BackendKind.TRITON_GRPC
                    or args.async_mode or args.request_intervals):
                sys.exit("error: --native-loadgen drives a socket gRPC "
                         "server (concurrency, --request-rate-range, or "
                         "--sequence mode); interval-file replay and "
                         "--async use the python engine")
            # modes the native sweep does not implement fail LOUDLY rather
            # than silently measuring something else
            unsupported = [
                ("-f/--filename", args.filename),
                ("--json-export", args.json_export),
                ("--latency-threshold", args.latency_threshold),
                ("--binary-search", args.binary_search),
                ("--collect-metrics", args.collect_metrics),
                ("--world-size > 1", args.world_size > 1),
                ("--measurement-mode count_windows",
                 args.measurement_mode == "count_windows"),
            ]
            offending = [name for name, on in unsupported if on]
            if offending:
                sys.exit("error: --native-loadgen does not support: "
                         + ", ".join(offending))
            if args.request_rate_range and args.sequence:
                sys.exit("error: --native-loadgen sequence mode is "
                         "closed-loop; pick --request-rate-range OR "
                         "--sequence")
            if args.shared_memory == "none" and args.input_data not in (
                    None, "random"):
                sys.exit("error: --native-loadgen wire mode generates "
                         "random tensor bytes; custom --input-data is "
                         "honored via --shared-memory system/tpu (regions "
                         "are staged with the real data)")
            if (loader.num_streams != 1 or loader.num_steps(0) != 1):
                sys.exit("error: --native-loadgen repeats one fixed request "
                         "(stream 0, step 0); dataset rotation needs the "
                         "python load engine")
            return _run_native_loadgen(args, control, loader, data_manager)

        if args.request_intervals:
            manager = CustomLoadManager(
                intervals_file=args.request_intervals, **common
            )
        elif args.request_rate_range:
            manager = RequestRateManager(
                distribution=args.request_distribution, **common
            )
        elif args.async_mode:
            from client_tpu.perf.load_manager import AsyncConcurrencyManager

            if (args.hermetic or kind != BackendKind.TRITON_GRPC
                    or args.sequence):
                sys.exit("error: --async requires a socket gRPC server and "
                         "a stateless workload (sequences ride streaming)")
            manager = AsyncConcurrencyManager(
                url=args.url,
                data_loader=loader,
                data_manager=data_manager,
                model_name=args.model_name,
                model_version=args.model_version,
                max_threads=args.max_threads,
            )
        else:
            manager = ConcurrencyManager(**common)

        # metrics (and the utilization probe's jax import + kernel compile)
        # come BEFORE the rendezvous barrier so multi-rank measurement
        # windows stay aligned after the barrier releases
        metrics = None
        if ((args.collect_local_tpu_metrics or args.probe_device_utilization)
                and not args.collect_metrics):
            print("warning: --collect-local-tpu-metrics/"
                  "--probe-device-utilization have no effect without "
                  "--collect-metrics", file=sys.stderr)
        if args.collect_metrics:
            from client_tpu.perf.metrics_manager import (
                DeviceUtilizationProbe,
                MetricsManager,
            )

            if args.hermetic:
                print("warning: --collect-metrics needs a socket server; "
                      "ignored with --hermetic", file=sys.stderr)
            else:
                probe = None
                if args.probe_device_utilization:
                    try:
                        probe = DeviceUtilizationProbe()
                    except Exception as e:
                        print(f"warning: utilization probe unavailable: {e}",
                              file=sys.stderr)
                url = args.metrics_url or f"http://{args.url}/metrics"
                metrics = MetricsManager(
                    url, interval_s=args.metrics_interval / 1e3,
                    include_local_devices=args.collect_local_tpu_metrics,
                    utilization_probe=probe,
                ).start()


        rendezvous = None
        if args.world_size > 1:
            from client_tpu.perf.rendezvous import Rendezvous

            rendezvous = Rendezvous(
                args.rank, args.world_size, args.rendezvous_addr
            )
            rendezvous.barrier()  # start measuring together (MPIBarrierWorld)

        profiler = InferenceProfiler(
            manager,
            backend=control,
            measurement_window_s=args.measurement_interval / 1e3,
            max_trials=args.max_trials,
            stability_threshold=args.stability_percentage / 100.0,
            percentile=args.percentile,
            verbose=args.verbose,
            metrics_manager=metrics,
            rendezvous=rendezvous,
            measurement_mode=args.measurement_mode,
            measurement_request_count=args.measurement_request_count,
        )
        if args.prefix_share is not None and engine is not None:
            # hermetic runs read the LM engine's prefix counters straight
            # from the in-process registry; socket runs have no per-level
            # counter deltas to offer (scrape aggregates only)
            registry = engine.metrics

            def _prefix_probe():
                def count(name):
                    return int(registry.get(name) or 0)

                return {
                    "hits": count("ctpu_lm_prefix_hits_total"),
                    "misses": count("ctpu_lm_prefix_misses_total"),
                    "prefill_tokens": count("ctpu_lm_prefill_tokens_total"),
                    "saved_tokens": count(
                        "ctpu_lm_prefill_tokens_saved_total"
                    ),
                }

            profiler.prefix_probe = _prefix_probe

        if args.speculative is not None and engine is not None:
            # same in-process counter-delta scheme as the prefix probe:
            # per-sweep acceptance comes from the engine registry, not a
            # scrape (delivered = accepted + one correction per verify)
            spec_registry = engine.metrics

            def _spec_probe():
                def count(name):
                    return int(spec_registry.get(name) or 0)

                return {
                    "proposed": count("ctpu_lm_spec_proposed_tokens_total"),
                    "accepted": count("ctpu_lm_spec_accepted_tokens_total"),
                    "lm_tokens": count("ctpu_lm_tokens_total"),
                }

            profiler.spec_probe = _spec_probe

        json_extra = {}
        try:
            if args.request_intervals:
                manager.start()
                results = [profiler.profile_level("custom_intervals", 0)]
            elif args.request_rate_range:
                start, end, step = _parse_range(args.request_rate_range, float)
                if args.binary_search and latency_limit_us:
                    # SLO-seeking capacity search: max sustainable QPS
                    # under the latency limit (open-loop arrivals)
                    results, best = profiler.profile_request_rate_binary(
                        start, end, latency_limit_us,
                        resolution=step if len(
                            args.request_rate_range.split(":")) > 2 else None,
                    )
                    # the search's verdict rides the JSON export: without
                    # it a consumer would have to re-derive pass/fail
                    # from the raw sweep points
                    json_extra["slo_search"] = {
                        "latency_limit_us": latency_limit_us,
                        "percentile": args.percentile,
                        "best_request_rate": (
                            None if best is None else best.level_value
                        ),
                        "best_throughput_infer_per_sec": (
                            None if best is None else best.throughput
                        ),
                    }
                    if best is not None:
                        print(
                            f"Max sustainable rate under SLO: "
                            f"{best.level_value} req/s "
                            f"({best.throughput:.1f} infer/sec)"
                        )
                    else:
                        print("SLO violated at every probed rate")
                else:
                    results = profiler.profile_request_rate_range(
                        start, end, step, latency_limit_us
                    )
            else:
                start, end, step = _parse_range(
                    args.concurrency_range or "1", int
                )
                if args.binary_search and latency_limit_us:
                    results, _ = profiler.profile_concurrency_binary(
                        start, end, latency_limit_us
                    )
                else:
                    results = profiler.profile_concurrency_range(
                        start, end, step, latency_limit_us
                    )
        finally:
            manager.cleanup()
            if metrics is not None:
                metrics.stop()

        print_summary(results, percentile=args.percentile)
        if rendezvous is not None:
            # rank-aggregated totals (the multi-model MPI mode's raison d'etre)
            per_rank = rendezvous.all_gather(
                [
                    {"level": s.level_value, "throughput": s.throughput,
                     "model": args.model_name}
                    for s in results
                ]
            )
            if args.rank == 0:
                print("\nAggregate across ranks:")
                for rank, levels in enumerate(per_rank):
                    for entry in levels:
                        print(
                            f"  rank {rank} [{entry['model']}] level "
                            f"{entry['level']}: {entry['throughput']:.1f} "
                            "infer/sec"
                        )
                total = sum(e["throughput"] for lv in per_rank for e in lv)
                print(f"  total: {total:.1f} infer/sec")
            rendezvous.close()
        if args.filename:
            write_csv(args.filename, results, verbose=args.verbose)
            print(f"wrote {args.filename}")
        if args.json_export:
            write_json(args.json_export, results, extra=json_extra)
            print(f"wrote {args.json_export}")
        return 0 if results and all(r.error_count == 0 for r in results) else 1
    except InferenceServerException as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        if churn_loop is not None:
            churn_loop.close()
        if replica_pool is not None:
            replica_pool.close()
        try:
            control.close()
        except Exception:
            pass
        if engine is not None:
            engine.close()
        if fake is not None:
            fake.stop()


if __name__ == "__main__":
    sys.exit(main())
