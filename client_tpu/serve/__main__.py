"""Standalone server entry point: ``python -m client_tpu.serve``."""

import argparse
import signal
import threading


def main():
    parser = argparse.ArgumentParser(description="client_tpu in-process KServe-v2 server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument(
        "--grpc-port",
        type=int,
        default=None,
        help="enable the gRPC frontend on this port",
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument(
        "--models",
        default="builtin",
        help="comma-separated model sets: builtin,jax,language (default: builtin)",
    )
    parser.add_argument(
        "--response-cache-entries", type=int, default=0,
        help="enable the content-addressed response cache with this many "
             "LRU entries (0 = off)",
    )
    parser.add_argument(
        "--response-cache-ttl", type=float, default=None,
        help="response-cache entry TTL in seconds (default: no expiry)",
    )
    parser.add_argument(
        "--coalescing", action="store_true",
        help="collapse identical concurrent requests into one dispatch",
    )
    parser.add_argument(
        "--tenant-inflight", type=int, default=None,
        help="per-tenant concurrent-request cap (429 + Retry-After beyond)",
    )
    parser.add_argument(
        "--tenant-rate", type=float, default=None,
        help="per-tenant request-rate quota in req/s (429 + Retry-After "
             "beyond)",
    )
    parser.add_argument(
        "--max-inflight", type=int, default=None,
        help="global concurrent-request cap (retryable 503 beyond)",
    )
    parser.add_argument(
        "--slo-p99-ms", type=float, default=None,
        help="arm the SLO watchdog: windowed p99 objective in ms for "
             "every model (breach increments ctpu_slo_breaches_total "
             "and dumps the flight recorder)",
    )
    parser.add_argument(
        "--slo-error-rate", type=float, default=None,
        help="SLO error-rate objective as a fraction (server faults only)",
    )
    parser.add_argument(
        "--flight-dir", default=None,
        help="directory for flight-recorder dumps (default: "
             "$TPU_FLIGHT_DIR, else the system temp dir)",
    )
    parser.add_argument(
        "--fleet-bind", default=None,
        help="join the cross-replica fleet tier: host:port for the peer "
             "server (host:0 picks a free port; printed at startup)",
    )
    parser.add_argument(
        "--fleet-peers", default="",
        help="comma-separated host:port peer fleet addresses",
    )
    parser.add_argument(
        "--replicate-k", type=int, default=1,
        help="peers each durable sequence snapshot / hot item is pushed "
             "to (0 = replication off)",
    )
    parser.add_argument(
        "--seq-quorum", choices=("any", "majority"), default="any",
        help="durable-sequence ack discipline: 'any' acks on best-effort "
             "push (a partition degrades to local-only durability), "
             "'majority' acks only after ceil((K+1)/2) peers stored the "
             "snapshot (quorum unreachable = retryable 503)",
    )
    args = parser.parse_args()

    from client_tpu.serve.models import model_sets

    sets = [s for s in args.models.split(",") if s != "builtin"]
    extra = model_sets(",".join(sets)) if sets else []

    from client_tpu.serve import Server

    cache = None
    if args.response_cache_entries > 0:
        from client_tpu.serve.frontdoor import ResponseCache

        cache = ResponseCache(
            max_entries=args.response_cache_entries,
            ttl_s=args.response_cache_ttl,
        )
    qos = None
    if args.tenant_inflight is not None or args.tenant_rate is not None:
        from client_tpu.serve.frontdoor import TenantQoS

        qos = TenantQoS(
            default_max_inflight=args.tenant_inflight,
            default_rate_per_s=args.tenant_rate,
        )

    slo = None
    if args.slo_p99_ms is not None or args.slo_error_rate is not None:
        from client_tpu.serve.slo import SloWatchdog

        objective = {}
        if args.slo_p99_ms is not None:
            objective["p99_ms"] = args.slo_p99_ms
        if args.slo_error_rate is not None:
            objective["error_rate"] = args.slo_error_rate
        slo = SloWatchdog(objectives={"*": objective})

    fleet = None
    if args.fleet_bind:
        from client_tpu.serve.fleet import FleetTier

        peers = [p.strip() for p in args.fleet_peers.split(",") if p.strip()]
        fleet = FleetTier(
            bind=args.fleet_bind,
            peers=peers,
            replicate_k=args.replicate_k,
            quorum=args.seq_quorum,
        ).start()

    server = Server(
        models=extra,
        http_port=args.http_port,
        grpc_port=args.grpc_port,
        host=args.host,
        verbose=args.verbose,
        with_default_models="builtin" in args.models.split(","),
        max_inflight=args.max_inflight,
        response_cache=cache,
        coalescing=args.coalescing,
        qos=qos,
        fleet=fleet,
        slo=slo,
    ).start()
    if args.flight_dir:
        server.engine.flight.dump_dir = args.flight_dir
    print(f"client_tpu.serve: HTTP on {server.http_address}", flush=True)
    if server.grpc_address:
        print(f"client_tpu.serve: gRPC on {server.grpc_address}", flush=True)
    if fleet is not None:
        print(
            f"client_tpu.serve: fleet peer port on {fleet.address} "
            f"(quorum={fleet.quorum}, replicate_k={fleet.replicate_k})",
            flush=True,
        )

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    server.stop()
    if fleet is not None:
        fleet.close()


if __name__ == "__main__":
    main()
