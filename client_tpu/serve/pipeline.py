"""Ensemble DAG scheduler: server-side model pipelines with device-resident
intermediates.

The reference treats ensembles as a first-class scheduler kind (ModelParser
NONE/DYNAMIC/SEQUENCE/ENSEMBLE/ENSEMBLE_SEQUENCE, per-composing-model stats
in InferenceProfiler/ReportWriter — SURVEY §2.3-2.5).  This module is that
scheduler for the in-process engine, replacing the old strictly-sequential
``_run_ensemble`` chain:

- **Parse + validate at load time** (:func:`build_dag`): ``ensemble_scheduling``
  steps become an explicit dependency DAG over ensemble tensors.  Cycles,
  unknown composing models, unmapped composing inputs, dangling tensors,
  producer/consumer dtype (and comparable-rank shape) mismatches, and
  composing models we cannot honor (sequence-stateful, decoupled) are all
  rejected with a 400 when the ensemble is *added or loaded* — not at the
  first unlucky infer.

- **Concurrent ready steps** (:class:`PipelineRunner`): independent branches
  run in parallel (the builtin ``simple_ensemble``'s two identity branches
  used to run serially); pure chains keep the zero-thread sequential path.

- **The normal scheduling path per step**: a step is dispatched exactly like
  a direct request to the composing model — through the model's dynamic
  batcher when the request is batchable (so ensemble steps from concurrent
  requests fuse into real device batches and wait in the per-tenant fair
  queue), directly otherwise — and records real per-composing-model
  statistics plus QUEUE_*/COMPUTE_* events on a per-step child span tagged
  with the step and ensemble names.

- **Device-resident intermediates**: when producer and consumer steps are
  both jax-backed, the ``jax.Array`` is handed off without a host
  round-trip — the place where the measured tpushm-vs-sysshm advantage
  compounds across a pipeline.  Host materialization happens only for
  python-platform consumers (counted in ``ctpu_ensemble_host_hops_total``)
  and at the DAG boundary when the response is rendered.

- **Failure semantics**: a failing step cancels every not-yet-started step,
  the error names the failing step, and the composing model's failure plus
  the ensemble-level failure are each recorded exactly once.  A composing
  model unloaded mid-flight surfaces as the engine's clean 400, never a
  hang.  Nested ensembles recurse through this same scheduler.
"""

import queue
import threading
import time

import numpy as np

from client_tpu.serve.tracing import RequestTrace
from client_tpu.tracing import gen_span_id
from client_tpu.utils import InferenceServerException

__all__ = [
    "ENSEMBLE_RESERVED_PARAMS",
    "EnsembleDag",
    "PipelineRunner",
    "build_dag",
]

# Request parameters that configure the *ensemble* request itself and must
# not leak into composing-model executions: sequence identity binds to the
# ensemble (composing sequence models are rejected at load), rendering hints
# apply only to the ensemble's own response, and decoupled-completion
# markers have no meaning mid-DAG.  Everything else (model-defined params
# like temperature/seed) threads through to every step.
ENSEMBLE_RESERVED_PARAMS = frozenset(
    {
        "sequence_id",
        "sequence_start",
        "sequence_end",
        "binary_data_output",
        "triton_enable_empty_final_response",
        "priority",
        "timeout",
    }
)


def step_params(params):
    """Request parameters forwarded to composing models (reserved keys
    stripped) — the fix for ensemble steps silently running with ``{}``."""
    return {
        k: v for k, v in (params or {}).items()
        if k not in ENSEMBLE_RESERVED_PARAMS
    }


def is_jax_model(model):
    """Whether a composing model consumes device arrays natively (its fn is
    jax-backed), so an upstream ``jax.Array`` hands off with zero host I/O."""
    platform = getattr(model, "platform", "") or ""
    backend = getattr(model, "backend", "") or ""
    return platform.startswith("jax") or backend.startswith("jax")


def _is_device_array(arr):
    from client_tpu.serve.dynamic_batcher import _is_device_array as _impl

    return _impl(arr)


class _Step:
    """One parsed ensemble step and its resolved dependencies."""

    __slots__ = ("index", "model_name", "input_map", "output_map", "deps",
                 "consumers")

    def __init__(self, index, model_name, input_map, output_map):
        self.index = index
        self.model_name = model_name
        self.input_map = dict(input_map)    # composing input <- ensemble tensor
        self.output_map = dict(output_map)  # composing output -> ensemble tensor
        self.deps = set()        # step indices whose outputs this step reads
        self.consumers = set()   # step indices reading this step's outputs

    @property
    def label(self):
        return f"step_{self.index}:{self.model_name}"


class EnsembleDag:
    """Validated dependency DAG for one ensemble model."""

    __slots__ = ("model_name", "steps", "is_chain", "order", "produced")

    def __init__(self, model_name, steps, is_chain, order, produced):
        self.model_name = model_name
        self.steps = steps
        self.is_chain = is_chain
        self.order = order        # step indices in topological order
        self.produced = produced  # ensemble tensors produced by steps


def _reject(ensemble_name, message):
    raise InferenceServerException(
        f"ensemble '{ensemble_name}': {message}", status="400"
    )


def _spec_maps(model):
    inputs = {t.name: t for t in model.inputs}
    outputs = {t.name: t for t in model.outputs}
    return inputs, outputs


def _shapes_conflict(src_dims, dst_dims):
    """True when two equal-rank specs pin conflicting fixed dims.  Specs of
    different rank are not comparable here — models like the builtin
    ``identity`` declare ``[-1]`` meaning "any shape"."""
    if len(src_dims) != len(dst_dims):
        return False
    return any(
        s >= 0 and d >= 0 and s != d for s, d in zip(src_dims, dst_dims)
    )


def build_dag(model, lookup):
    """Parse + validate *model*'s ensemble_scheduling into an EnsembleDag.

    *lookup* maps a model name to its Model (or None).  Raises a 400
    InferenceServerException on any structural problem so the ensemble is
    rejected at add/load time, never at infer time.
    """
    name = model.name
    if not model.ensemble_steps:
        _reject(name, "ensemble_scheduling has no steps")
    ens_inputs, ens_outputs = _spec_maps(model)

    steps = []
    producer = {}        # ensemble tensor -> producing step index
    produced_spec = {}   # ensemble tensor -> composing output TensorSpec
    for i, raw in enumerate(model.ensemble_steps):
        sub_name = raw.get("model_name")
        if not sub_name:
            _reject(name, f"step {i} has no model_name")
        step = _Step(i, sub_name, raw.get("input_map") or {},
                     raw.get("output_map") or {})
        if sub_name == name:
            _reject(name, f"step {i} refers to the ensemble itself")
        sub = lookup(sub_name)
        if sub is None:
            _reject(
                name,
                f"step {i} names unknown composing model '{sub_name}'",
            )
        if getattr(sub, "stateful", False):
            _reject(
                name,
                f"step {i}: composing model '{sub_name}' uses sequence "
                "batching; ENSEMBLE over sequence models is not supported "
                "(sequence state binds to the composing model, not the "
                "ensemble request)",
            )
        if getattr(sub, "decoupled", False):
            _reject(
                name,
                f"step {i}: composing model '{sub_name}' is decoupled; "
                "a mid-DAG response stream cannot be honored",
            )
        sub_inputs, sub_outputs = _spec_maps(sub)
        for ci in step.input_map:
            if ci not in sub_inputs:
                _reject(
                    name,
                    f"step {i} input_map names '{ci}', which is not an "
                    f"input of composing model '{sub_name}'",
                )
        missing = [
            t.name for t in sub.inputs
            if t.name not in step.input_map and not t.optional
        ]
        if missing:
            _reject(
                name,
                f"step {i} leaves composing model '{sub_name}' inputs "
                f"{missing} unmapped",
            )
        for co, et in step.output_map.items():
            if co not in sub_outputs:
                _reject(
                    name,
                    f"step {i} output_map names '{co}', which is not an "
                    f"output of composing model '{sub_name}'",
                )
            if et in producer:
                _reject(
                    name,
                    f"tensor '{et}' is produced by both step "
                    f"{producer[et]} and step {i}",
                )
            if et in ens_inputs:
                _reject(
                    name,
                    f"step {i} produces tensor '{et}', which shadows an "
                    "ensemble input",
                )
            producer[et] = i
            produced_spec[et] = sub_outputs[co]
        steps.append(step)

    # Resolve each step input to its source (ensemble input or producing
    # step) and check dtype/shape agreement producer -> consumer.
    for step in steps:
        sub = lookup(step.model_name)
        sub_inputs, _ = _spec_maps(sub)
        for ci, et in step.input_map.items():
            dst = sub_inputs[ci]
            if et in ens_inputs:
                src = ens_inputs[et]
            elif et in producer:
                if producer[et] == step.index:
                    _reject(
                        name,
                        f"step {step.index} reads its own output tensor "
                        f"'{et}' (self-cycle)",
                    )
                step.deps.add(producer[et])
                src = produced_spec[et]
            else:
                _reject(
                    name,
                    f"step {step.index} reads tensor '{et}', which is "
                    "neither an ensemble input nor produced by any step "
                    "(dangling tensor)",
                )
            if src.datatype != dst.datatype:
                _reject(
                    name,
                    f"step {step.index} input '{ci}' expects "
                    f"{dst.datatype} but tensor '{et}' carries "
                    f"{src.datatype}",
                )
            if _shapes_conflict(src.dims, dst.dims):
                _reject(
                    name,
                    f"step {step.index} input '{ci}' dims {dst.dims} "
                    f"conflict with tensor '{et}' dims {src.dims}",
                )
    for step in steps:
        for d in step.deps:
            steps[d].consumers.add(step.index)

    # Every ensemble output must be produced (or be a pass-through input),
    # with matching dtype.
    for out_name, spec in ens_outputs.items():
        if out_name in ens_inputs:
            src = ens_inputs[out_name]
        elif out_name in producer:
            src = produced_spec[out_name]
        else:
            _reject(
                name,
                f"output tensor '{out_name}' is not produced by any step",
            )
        if src.datatype != spec.datatype:
            _reject(
                name,
                f"output tensor '{out_name}' is declared {spec.datatype} "
                f"but its producer carries {src.datatype}",
            )
        if _shapes_conflict(src.dims, spec.dims):
            _reject(
                name,
                f"output tensor '{out_name}' dims {spec.dims} conflict "
                f"with its producer's dims {src.dims}",
            )

    # Kahn topological check: leftover steps form a cycle.  The same walk
    # detects whether the DAG is a pure chain (at most one step ready at
    # any point) — chains skip the threaded scheduler entirely.
    indegree = {s.index: len(s.deps) for s in steps}
    ready = sorted(i for i, d in indegree.items() if d == 0)
    scheduled = []
    is_chain = True
    while ready:
        if len(ready) > 1:
            is_chain = False
        i = ready.pop(0)
        scheduled.append(i)
        for c in sorted(steps[i].consumers):
            indegree[c] -= 1
            if indegree[c] == 0:
                ready.append(c)
    if len(scheduled) != len(steps):
        stuck = sorted(set(indegree) - set(scheduled))
        _reject(
            name,
            "ensemble_scheduling steps "
            f"{[steps[i].label for i in stuck]} form a dependency cycle",
        )
    return EnsembleDag(name, steps, is_chain, scheduled, frozenset(producer))


class _StepOutcome:
    __slots__ = ("index", "outputs", "error", "work_ns")

    def __init__(self, index, outputs=None, error=None, work_ns=0):
        self.index = index
        self.outputs = outputs
        self.error = error
        self.work_ns = work_ns


class PipelineRunner:
    """Executes validated ensemble DAGs against an InferenceEngine.

    One runner per engine; all state is per-call, so concurrent requests
    share it freely.  Steps ride each composing model's normal scheduling
    path (dynamic batcher or direct dispatch) — this class only sequences
    them and moves tensors between steps.
    """

    def __init__(self, engine):
        self._engine = engine

    # -- public entry --------------------------------------------------------

    def run(self, model, inputs, params, trace=None, tenant=""):
        """Execute *model*'s DAG over *inputs*; returns
        ``(outputs, work_ns)`` where *outputs* maps the ensemble's declared
        output tensors and *work_ns* is the summed per-step duration — the
        exact quantity recorded as the ensemble's ``compute_infer`` so
        per-composing-model statistics reconcile against ensemble totals.
        """
        dag = getattr(model, "_dag", None)
        if dag is None:
            # engine-level callers always validate at add/load; a model
            # handed in by other means validates here, same 400 contract
            dag = build_dag(model, self._engine._model_lookup())
            model._dag = dag
        metrics = self._engine.metrics
        metrics.inc(
            "ctpu_ensemble_requests_total", {"model": model.name},
            help_="Requests executed by the ensemble DAG scheduler",
        )
        forwarded = step_params(params)
        pool = dict(inputs)
        if dag.is_chain:
            work_ns = self._run_chain(model, dag, pool, forwarded, trace,
                                      tenant)
        else:
            work_ns = self._run_parallel(model, dag, pool, forwarded, trace,
                                         tenant)
        missing = [t.name for t in model.outputs if t.name not in pool]
        if missing:
            raise InferenceServerException(
                f"ensemble '{model.name}' produced no tensor(s) {missing}",
                status="500",
            )
        return {t.name: pool[t.name] for t in model.outputs}, work_ns

    # -- schedulers ----------------------------------------------------------

    def _run_chain(self, model, dag, pool, forwarded, trace, tenant):
        """Sequential path for pure chains: no threads, no queue."""
        work_ns = 0
        for position, index in enumerate(dag.order):
            outcome = self._run_step(model, dag, dag.steps[index], pool,
                                     forwarded, trace, tenant)
            work_ns += outcome.work_ns
            if outcome.error is not None:
                self._note_cancelled(model, len(dag.steps) - position - 1)
                raise outcome.error
            pool.update(outcome.outputs)
        return work_ns

    def _run_parallel(self, model, dag, pool, forwarded, trace, tenant):
        """Event-driven scheduler: every ready step dispatches immediately
        on its own worker thread; completions release their consumers.  On
        a step failure nothing new dispatches (the cancellation contract) —
        already-running steps are drained so no worker outlives the call.
        """
        done = queue.Queue()
        pool_lock = threading.Lock()
        indegree = {s.index: len(s.deps) for s in dag.steps}
        ready = [dag.steps[i] for i, d in sorted(indegree.items()) if d == 0]
        inflight = 0
        executed = 0
        failures = 0
        failure = None
        work_ns = 0

        def worker(step):
            with pool_lock:
                snapshot = dict(pool)
            try:
                outcome = self._run_step(model, dag, step, snapshot,
                                         forwarded, trace, tenant)
            except BaseException as e:  # noqa: BLE001 - thread boundary:
                # the worker must always post exactly one outcome or the
                # coordinator hangs on done.get()
                outcome = _StepOutcome(
                    step.index, error=self._step_error(model, step, e)
                )
            done.put(outcome)

        while ready or inflight:
            if len(ready) == 1 and not inflight:
                # a lone ready step with nothing to overlap runs directly
                # on the calling thread — chain-shaped stretches of a wide
                # DAG spawn no threads, and without the worker's
                # thread-boundary net KeyboardInterrupt/SystemExit
                # propagate exactly like the chain path (no snapshot
                # either: nothing in flight can mutate the pool)
                done.put(self._run_step(model, dag, ready.pop(), pool,
                                        forwarded, trace, tenant))
                inflight += 1
            else:
                # thread-per-ready-step, deliberately not a shared bounded
                # pool: steps block on the batcher (and nested ensembles
                # dispatch steps of their own), so a finite pool could
                # deadlock parent steps waiting on children with no slot.
                # Per-wave thread churn (~100us/step) is noise next to
                # batcher queue+dispatch time.
                for step in ready:
                    t = threading.Thread(
                        target=worker, args=(step,), daemon=True,
                        name=f"ensemble-{model.name}-{step.label}",
                    )
                    try:
                        t.start()
                    except RuntimeError:
                        # thread limit hit: degrade to inline execution
                        worker(step)
                    inflight += 1
                ready = []
            # bounded: every dispatched worker always posts exactly one
            # outcome, success or failure
            outcome = done.get()
            inflight -= 1
            work_ns += outcome.work_ns
            if outcome.error is not None:
                failures += 1
                if failure is None:
                    failure = outcome.error
                continue  # drain remaining in-flight steps, dispatch nothing
            executed += 1
            with pool_lock:
                pool.update(outcome.outputs)
            if failure is None:
                for c in sorted(dag.steps[outcome.index].consumers):
                    indegree[c] -= 1
                    if indegree[c] == 0:
                        ready.append(dag.steps[c])
        if failure is not None:
            # dispatched steps all posted (executed + failures); the rest
            # were never dispatched
            self._note_cancelled(
                model, len(dag.steps) - executed - failures
            )
            raise failure
        return work_ns

    def _note_cancelled(self, model, count):
        if count > 0:
            self._engine.metrics.inc(
                "ctpu_ensemble_cancelled_steps_total",
                {"model": model.name}, value=count,
                help_="DAG steps never dispatched because an earlier step "
                      "failed",
            )

    # -- one step ------------------------------------------------------------

    def _run_step(self, ens, dag, step, pool, forwarded, trace, tenant):
        """Execute one step; failures come back in the outcome so the
        schedulers control cancellation uniformly.  Only ``Exception`` is
        converted — KeyboardInterrupt/SystemExit propagate (the parallel
        scheduler's worker adds its own thread-boundary net)."""
        engine = self._engine
        metrics = engine.metrics
        t0 = time.monotonic_ns()
        step_trace = self._step_span(trace, ens, step)
        try:
            # repository lookup per dispatch: a composing model unloaded
            # mid-flight fails THIS step with the engine's clean 400
            sub = engine.get_model(step.model_name, "")
            sub_inputs, hops, handoffs = self._map_inputs(
                step, sub, pool, dag.produced
            )
            t_in1 = time.monotonic_ns()
            if hops:
                metrics.inc(
                    "ctpu_ensemble_host_hops_total", {"model": ens.name},
                    value=hops,
                    help_="Device intermediates materialized to host for a "
                          "non-jax consumer step",
                )
            if handoffs:
                metrics.inc(
                    "ctpu_ensemble_device_handoffs_total",
                    {"model": ens.name}, value=handoffs,
                    help_="Device intermediates handed to a jax-backed "
                          "consumer step with zero host I/O",
                )
            out, total_ns = self._dispatch(
                ens, step, sub, sub_inputs, forwarded, step_trace, tenant,
                t0, t_in1,
            )
            outputs = {}
            for co, et in step.output_map.items():
                if co not in out:
                    raise InferenceServerException(
                        f"composing model '{sub.name}' produced no output "
                        f"'{co}'", status="500",
                    )
                outputs[et] = out[co]
            metrics.inc(
                "ctpu_ensemble_steps_total",
                {"model": ens.name, "composing_model": step.model_name},
                help_="Ensemble DAG steps executed",
            )
            if step_trace is not None:
                engine.tracer.complete(step_trace)
            return _StepOutcome(step.index, outputs=outputs,
                                work_ns=total_ns)
        except Exception as e:
            metrics.inc(
                "ctpu_ensemble_step_failures_total",
                {"model": ens.name, "composing_model": step.model_name},
                help_="Ensemble DAG steps that failed",
            )
            err = self._step_error(ens, step, e)
            if step_trace is not None:
                step_trace.error = err.message()
                engine.tracer.complete(step_trace)
            return _StepOutcome(
                step.index, error=err, work_ns=time.monotonic_ns() - t0
            )

    def _dispatch(self, ens, step, sub, sub_inputs, forwarded, step_trace,
                  tenant, t0, t_in1):
        """Route one step through the composing model's normal scheduling
        path and record its statistics under the composing model's name.
        Returns ``(result_arrays, total_ns)``; *total_ns* is exactly what
        lands in the composing model's success duration, so summed step
        durations reconcile with the ensemble's compute_infer total."""
        engine = self._engine
        sub_stats = engine._stats[sub.name]
        if sub.ensemble_steps:  # nested ensemble: recurse, record its stats
            try:
                out, work_ns = self.run(
                    sub, sub_inputs, forwarded, trace=step_trace,
                    tenant=tenant,
                )
            except BaseException:
                sub_stats.record(False, time.monotonic_ns() - t0, 0, 0, 0)
                raise
            total_ns = time.monotonic_ns() - t0
            sub_stats.record(
                True, total_ns, work_ns, t_in1 - t0, 0,
                batch=_rows_of(sub, sub_inputs),
            )
            return out, total_ns
        try:
            if self._batchable(sub, sub_inputs, forwarded):
                weight = (
                    engine.qos.weight(tenant)
                    if engine.qos is not None else 1.0
                )
                # the batcher stamps QUEUE_END/COMPUTE_* on the step span at
                # dispatch/completion and records execution-level stats
                # (queue/compute split) under the composing model's name
                out = engine._batcher_for(sub).submit(
                    sub_inputs, trace=step_trace, tenant=tenant,
                    weight=weight,
                )
                total_ns = time.monotonic_ns() - t0
                sub_stats.record_request_success(total_ns)
                return out, total_ns
            if step_trace is not None:
                w_now = time.time_ns()
                step_trace.event("QUEUE_END", w_now)
                step_trace.event("COMPUTE_START", w_now)
                step_trace.event("COMPUTE_INPUT_END")
            with engine.busy:
                out = sub.fn(sub_inputs, forwarded, None)
            t_inf1 = time.monotonic_ns()
            if step_trace is not None:
                step_trace.event("COMPUTE_END")
            t_end = time.monotonic_ns()
            total_ns = t_end - t0
            # real phase split (the old chain stuffed the whole step into
            # infer_ns): input = tensor mapping/residency conversion,
            # infer = the model call, output = the output-map fanout
            sub_stats.record(
                True, total_ns, t_inf1 - t_in1, t_in1 - t0, t_end - t_inf1,
                batch=_rows_of(sub, sub_inputs),
            )
            return out, total_ns
        except BaseException:
            sub_stats.record(False, time.monotonic_ns() - t0, 0, 0, 0)
            raise

    @staticmethod
    def _batchable(sub, sub_inputs, forwarded):
        from client_tpu.serve.dynamic_batcher import batchable_request

        return batchable_request(sub, sub_inputs, forwarded, None, {})

    def _map_inputs(self, step, sub, pool, produced):
        """Composing-model inputs from the tensor pool, honoring residency:
        jax-backed consumers take device arrays as-is (zero host I/O);
        python consumers get host arrays.  Only *intermediates* — tensors
        produced by an upstream step — count toward the handoff/hop
        metrics; an ensemble boundary input arriving as a device array
        (tpushm) is not a hop the pipeline saved or spent."""
        jax_backed = is_jax_model(sub)
        sub_inputs = {}
        hops = 0
        handoffs = 0
        for ci, et in step.input_map.items():
            try:
                arr = pool[et]
            except KeyError:
                raise InferenceServerException(
                    f"tensor '{et}' not available for step "
                    f"'{step.model_name}'", status="500",
                ) from None
            if _is_device_array(arr):
                if jax_backed:
                    handoffs += et in produced
                else:
                    arr = np.asarray(arr)  # host materialization
                    hops += et in produced
            sub_inputs[ci] = arr
        return sub_inputs, hops, handoffs

    @staticmethod
    def _step_span(trace, ens, step):
        """A child span for one step under the request's trace (None when
        the request was not sampled).  Tagged with the step label and the
        owning ensemble so per-branch timelines read straight off the
        trace file."""
        if trace is None:
            return None
        span = RequestTrace(
            trace.trace_id,
            gen_span_id(),
            parent_span_id=trace.span_id,
            model_name=step.model_name,
            model_version="",
            protocol=getattr(trace, "protocol", ""),
            seq=getattr(trace, "seq", 0),
            step=step.label,
            ensemble=ens.name,
        )
        span.tenant = getattr(trace, "tenant", "")
        span.event("QUEUE_START")
        return span

    @staticmethod
    def _step_error(ens, step, exc):
        if isinstance(exc, InferenceServerException):
            message = exc.message() or str(exc)
            if message.startswith(f"ensemble '{ens.name}' step"):
                return exc  # already named by a nested level
            return InferenceServerException(
                f"ensemble '{ens.name}' step {step.index} "
                f"('{step.model_name}') failed: {message}",
                status=exc.status() or "500",
                debug_details=exc.debug_details(),
            )
        return InferenceServerException(
            f"ensemble '{ens.name}' step {step.index} "
            f"('{step.model_name}') failed: {exc}",
            status="500", debug_details=exc,
        )


def _rows_of(model, inputs):
    if getattr(model, "max_batch_size", 0) <= 0:
        return 1
    for arr in inputs.values():
        shape = getattr(arr, "shape", None)
        if shape:
            return int(shape[0])
    return 1
