"""KServe-v2 HTTP/REST frontend for the in-process inference engine.

Stdlib ThreadingHTTPServer; implements the endpoints the client surface uses:
health, metadata, config, infer (binary tensor extension + compression),
repository control, statistics, trace/log settings, and shared-memory verbs
(system / tpu; cuda answers with an explicit not-supported error since there is
no cudart anywhere in this framework).
"""

import base64
import json
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote

from client_tpu import _codec
from client_tpu.serve import frontdoor, model_runtime
from client_tpu.utils import InferenceServerException

_MODEL_URI = re.compile(
    r"^/v2/models/(?P<model>[^/]+)(?:/versions/(?P<version>[^/]+))?(?P<rest>/.*)?$"
)
_SHM_URI = re.compile(
    r"^/v2/(?P<kind>systemsharedmemory|cudasharedmemory|tpusharedmemory)"
    r"(?:/region/(?P<region>[^/]+))?/(?P<verb>status|register|unregister)$"
)
_REPO_URI = re.compile(
    r"^/v2/repository/(?:index|models/(?P<model>[^/]+)/(?P<verb>load|unload))$"
)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # responses go out as header-write + body-write; with Nagle on, the body
    # write stalls ~40ms waiting for the client's delayed ACK of the headers
    disable_nagle_algorithm = True
    engine = None  # set by subclassing in HttpFrontend
    verbose = False

    def log_message(self, fmt, *args):  # quiet by default
        if self.verbose:
            super().log_message(fmt, *args)

    # -- plumbing ------------------------------------------------------------

    def _body(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        return _codec.decompress(body, self.headers.get("Content-Encoding"))

    def _send(self, status, body=b"", headers=None):
        self.send_response(status)
        headers = headers or {}
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        if not any(k.lower() == "content-type" for k in headers):
            self.send_header("Content-Type", "application/json")
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _send_json(self, obj, status=200):
        self._send(status, json.dumps(obj).encode("utf-8"))

    def _send_error_json(self, exc):
        status = 400
        if isinstance(exc, InferenceServerException) and exc.status():
            try:
                status = int(exc.status())
            except ValueError:
                pass
        msg = (
            exc.message()
            if isinstance(exc, InferenceServerException)
            else str(exc)
        )
        headers = None
        if status in (429, 503):
            # overload/drain shedding is retryable: tell well-behaved
            # clients when to come back (client retry policies cap this
            # hint at their own max backoff).  QoS quota rejections carry
            # a computed hint (when the token bucket refills); others
            # default to 1s.  RFC 9110 Retry-After is integer
            # delta-seconds — a fractional value would be rejected by
            # spec-strict third-party parsers, silencing the hint exactly
            # when it matters.
            hint = getattr(exc, "retry_after_s", None)
            headers = {
                "Retry-After": str(max(1, math.ceil(float(hint))))
                if hint else "1"
            }
        self._send(status, json.dumps({"error": msg}).encode("utf-8"), headers)

    # -- request routing -----------------------------------------------------

    def do_GET(self):
        try:
            self._route_get()
        except InferenceServerException as e:
            self._send_error_json(e)
        except Exception as e:  # pragma: no cover - defensive
            self._send_error_json(InferenceServerException(str(e), status="500"))

    def do_POST(self):
        try:
            # Drain the request body up front: error paths that respond without
            # reading it would desync subsequent requests on this keep-alive
            # connection.
            self._post_body = self._body()
            self._route_post()
        except InferenceServerException as e:
            self._send_error_json(e)
        except json.JSONDecodeError as e:
            self._send_error_json(
                InferenceServerException(f"malformed request JSON: {e}", status="400")
            )
        except Exception as e:  # pragma: no cover - defensive
            self._send_error_json(InferenceServerException(str(e), status="500"))

    def _route_get(self):
        path = self.path.split("?", 1)[0]
        eng = self.engine
        if path == "/v2/health/live":
            return self._send(200)
        if path == "/v2/health/ready":
            # drain() flips readiness false so load balancers stop routing
            # here while in-flight work finishes
            if eng.ready():
                return self._send(200)
            return self._send(
                503, json.dumps({"error": "server is draining"}).encode("utf-8")
            )
        if path == "/metrics":
            from client_tpu.serve.metrics import render_metrics

            body = render_metrics(eng).encode("utf-8")
            return self._send(
                200, body, {"Content-Type": "text/plain; version=0.0.4"}
            )
        if path in ("/v2", "/v2/"):
            return self._send_json(
                {
                    "name": model_runtime.SERVER_NAME,
                    "version": model_runtime.SERVER_VERSION,
                    "extensions": model_runtime.SERVER_EXTENSIONS,
                }
            )
        if path == "/v2/logging":
            return self._send_json(eng.log_settings)
        if path == "/v2/trace/setting":
            return self._send_json(eng.trace_settings)
        if path == "/v2/debug/flight":
            # flight-recorder ring as JSON-lines (the on-demand dump);
            # ?dump=1 instead writes a file server-side and reports it
            query = parse_qs((self.path.split("?", 1) + [""])[1])
            if query.get("dump", [""])[-1] == "1":
                path_written = eng.flight.dump("debug_endpoint")
                return self._send_json({
                    "dumped": path_written,
                    "events": len(eng.flight.snapshot()),
                })
            body = eng.flight.render("debug_endpoint")
            return self._send(
                200, body.encode("utf-8"),
                {"Content-Type": "application/jsonl"},
            )
        if path == "/v2/debug/prof":
            # continuous profiler's windowed rollup (serve/prof.py):
            # per-phase attribution, tick counts, per-model MFU /
            # compute share for this engine and every adopted child.
            # ?window=SECONDS bounds the rollup (0 = whole ring).
            query = parse_qs((self.path.split("?", 1) + [""])[1])
            try:
                window = float(query.get("window", [""])[-1])
            except ValueError:
                window = None
            return self._send_json(eng.prof.report(window_s=window))
        if path == "/v2/debug/slo":
            slo = eng.slo
            return self._send_json(slo.check_now() if slo is not None else {})
        if path == "/v2/models/stats":
            return self._send_json({"model_stats": eng.statistics()})
        shm = _SHM_URI.match(path)
        if shm and shm.group("verb") == "status":
            return self._shm_status(shm)
        m = _MODEL_URI.match(path)
        if m:
            model = unquote(m.group("model"))
            version = unquote(m.group("version")) if m.group("version") else ""
            rest = m.group("rest") or ""
            if rest == "/ready":
                if eng.model_ready(model, version):
                    return self._send(200)
                return self._send(400, json.dumps({"error": "model not ready"}).encode())
            if rest == "/config":
                return self._send_json(eng.get_model(model, version).config())
            if rest == "/stats":
                return self._send_json(
                    {"model_stats": eng.statistics(model, version)}
                )
            if rest == "/trace/setting":
                return self._send_json(eng.trace_settings)
            if rest == "":
                return self._send_json(eng.get_model(model, version).metadata())
        raise InferenceServerException(f"unknown endpoint {path}", status="404")

    def _route_post(self):
        path = self.path.split("?", 1)[0]
        eng = self.engine
        if path == "/v2/repository/index":
            body = self._post_body
            ready = False
            if body:
                ready = bool(json.loads(body.decode("utf-8")).get("ready"))
            return self._send_json(eng.repository_index(ready))
        repo = _REPO_URI.match(path)
        if repo and repo.group("model"):
            model_name = unquote(repo.group("model"))
            if repo.group("verb") == "load":
                payload = (
                    json.loads(self._post_body.decode("utf-8"))
                    if self._post_body
                    else {}
                )
                params = payload.get("parameters", {}) or {}
                config = params.get("config")
                files = {
                    k: base64.b64decode(v)
                    for k, v in params.items()
                    if k.startswith("file:")
                }
                eng.load_model(
                    model_name,
                    config_override=json.loads(config) if config else None,
                    files=files or None,
                )
            else:
                eng.unload_model(model_name)
            return self._send_json({})
        shm = _SHM_URI.match(path)
        if shm:
            return self._shm_action(shm)
        if path == "/v2/logging":
            settings = json.loads(self._post_body.decode("utf-8") or "{}")
            eng.log_settings.update(
                {k: v for k, v in settings.items() if v is not None}
            )
            return self._send_json(eng.log_settings)
        if path == "/v2/trace/setting":
            settings = json.loads(self._post_body.decode("utf-8") or "{}")
            return self._send_json(eng.update_trace_settings(settings))
        m = _MODEL_URI.match(path)
        if m and (m.group("rest") or "") == "/trace/setting":
            settings = json.loads(self._post_body.decode("utf-8") or "{}")
            return self._send_json(eng.update_trace_settings(settings))
        if m and (m.group("rest") or "") == "/infer":
            return self._infer(
                unquote(m.group("model")),
                unquote(m.group("version")) if m.group("version") else "",
            )
        raise InferenceServerException(f"unknown endpoint {path}", status="404")

    # -- handlers ------------------------------------------------------------

    def _shm_status(self, match):
        kind = match.group("kind")
        region = unquote(match.group("region")) if match.group("region") else ""
        eng = self.engine
        if kind == "systemsharedmemory":
            regions = eng.shm.system_status(region or None)
        elif kind == "tpusharedmemory":
            regions = eng.shm.tpu_status(region or None)
        else:
            regions = {}
        return self._send_json(list(regions.values()))

    def _shm_action(self, match):
        kind = match.group("kind")
        region = unquote(match.group("region")) if match.group("region") else ""
        verb = match.group("verb")
        eng = self.engine
        if kind == "cudasharedmemory" and verb != "unregister":
            raise InferenceServerException(
                "CUDA shared memory is not supported by this server "
                "(use tpusharedmemory)",
                status="400",
            )
        body = self._post_body
        payload = json.loads(body.decode("utf-8")) if body else {}
        if kind == "systemsharedmemory":
            if verb == "register":
                eng.shm.register_system(
                    region,
                    payload["key"],
                    payload.get("offset", 0),
                    payload["byte_size"],
                )
            elif verb == "unregister":
                eng.shm.unregister_system(region or None)
        elif kind == "tpusharedmemory":
            if verb == "register":
                raw = base64.b64decode(payload["raw_handle"]["b64"])
                eng.shm.register_tpu(
                    region, raw, payload.get("device_id", 0), payload["byte_size"]
                )
            elif verb == "unregister":
                eng.shm.unregister_tpu(region or None)
        else:  # cuda unregister: accept as no-op for symmetry
            pass
        return self._send_json({})

    def _infer(self, model, version):
        body = self._post_body
        # wire-path profiling (serve/prof.py): deserialize / execute-wait
        # / serialize / send splits, committed as one "http" tick so the
        # idle-link question becomes a ranked table
        ptick = self.engine.wire_prof.start_tick("http")
        t_mark = time.perf_counter()
        header_length = self.headers.get("Inference-Header-Content-Length")
        request, binary = _codec.parse_infer_request_body(
            body, int(header_length) if header_length is not None else None
        )
        ptick.add("deserialize", time.perf_counter() - t_mark)
        # request tracing: joins the client's trace id when the request
        # carries a W3C traceparent header (see serve/tracing.py)
        trace = self.engine.tracer.sample(
            self.headers.get("traceparent"), model_name=model,
            model_version=version, protocol="http",
        )
        # tenant identity for QoS/fair-queueing (serve/frontdoor.py);
        # header lookup is case-insensitive per the email-message API
        tenant = self.headers.get(frontdoor.TENANT_HEADER) or ""
        if trace is not None:
            trace.event("REQUEST_START")
        try:
            t_mark = time.perf_counter()
            result = self.engine.execute(
                model, version, request, binary, trace=trace, tenant=tenant
            )
            if not isinstance(result, tuple):  # decoupled (generator/list)
                # consuming it releases its admission slot
                responses = list(result)
                if len(responses) != 1:
                    raise InferenceServerException(
                        f"model '{model}' is decoupled; HTTP requires exactly "
                        f"one response but got {len(responses)} — use gRPC "
                        "streaming",
                        status="400",
                    )
                result = responses[0]
            ptick.add("wait", time.perf_counter() - t_mark)
            response_json, blobs = result
            t_mark = time.perf_counter()
            body, json_size = _codec.build_infer_response_body(
                response_json, blobs
            )
            headers = {}
            if json_size is not None:
                headers["Inference-Header-Content-Length"] = str(json_size)
            accept = (self.headers.get("Accept-Encoding") or "").lower()
            for algo in ("gzip", "deflate"):
                if algo in accept:
                    body = _codec.compress(body, algo)
                    headers["Content-Encoding"] = algo
                    break
            ptick.add("serialize", time.perf_counter() - t_mark)
            t_mark = time.perf_counter()
            self._send(200, body, headers)
            ptick.add("send", time.perf_counter() - t_mark)
            if trace is not None:
                trace.event("RESPONSE_SENT")
        except Exception as e:
            if trace is not None:
                trace.error = str(e)
            raise
        finally:
            self.engine.wire_prof.finish(ptick)
            if trace is not None:
                self.engine.tracer.complete(trace)


class HttpFrontend:
    """Threaded HTTP server bound to an InferenceEngine."""

    def __init__(self, engine, host="127.0.0.1", port=0, verbose=False):
        handler = type(
            "BoundHandler", (_Handler,), {"engine": engine, "verbose": verbose}
        )
        # socketserver's default listen backlog is 5: a connection burst
        # (many tenants arriving at once) overflows the accept queue and
        # the spilled clients pay a full TCP SYN-retransmit timeout (~1s)
        # before connecting — a 50x tail-latency cliff invisible in any
        # server-side metric.  A multi-tenant front door needs a real
        # backlog; admission control above decides who gets served.
        server_cls = type(
            "FrontDoorHTTPServer", (ThreadingHTTPServer,),
            {"request_queue_size": 128},
        )
        self._httpd = server_cls((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread = None

    @property
    def address(self):
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="client_tpu-http-server", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
