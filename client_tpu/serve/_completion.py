"""Shared device-completion observer for async-dispatch bookkeeping.

A single daemon thread per observer waits on watched device results and runs
a per-item callback at completion — the mechanism the engine's duty-cycle
metric (BusyTracker spans) and the dynamic batcher's pipeline backpressure
both close through.  One wait covers a whole backlog: the observer blocks on
*every* array in the drained batch (not just the newest — watch order across
request threads is not dispatch order, and multi-device models have no
single stream), then fires the callbacks.  Host-only results complete
immediately on the caller thread.
"""

import threading


def _completion_arrays(result, out=None):
    """Arrays worth waiting on from a result pytree (nested dict/list/tuple
    of arrays — e.g. a fused batch's per-part output dict of tuples)."""
    if out is None:
        out = []
    if isinstance(result, dict):
        for v in result.values():
            _completion_arrays(v, out)
    elif isinstance(result, (list, tuple)):
        for v in result:
            _completion_arrays(v, out)
    elif hasattr(result, "block_until_ready"):
        out.append(result)
    return out


class CompletionObserver:
    def __init__(self, name="completion-observer"):
        self._name = name
        self._cv = threading.Condition()
        self._backlog = []  # (arrays, callback)
        self._closed = False
        self._thread = None

    def watch(self, result, callback):
        """Run *callback* once every device array in *result* has completed.

        Host results (nothing to wait on) run the callback inline.  Watches
        arriving after close() — e.g. a batcher thread that outlived its
        bounded shutdown join — block inline on the caller thread and still
        run the callback, so no span/semaphore/counter ever leaks.
        """
        arrays = _completion_arrays(result)
        if not arrays:
            callback()
            return
        with self._cv:
            if not self._closed:
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._loop, name=self._name, daemon=True
                    )
                    self._thread.start()
                self._backlog.append((arrays, callback))
                self._cv.notify()
                return
        self._settle(arrays)
        callback()

    @staticmethod
    def _settle(arrays):
        try:
            import jax

            jax.block_until_ready(arrays)
        except Exception:  # noqa: BLE001 - failed results still complete
            pass

    def _loop(self):
        # one guard per pass (the BG-THREAD-CRASH shape): a raising
        # completion callback must not kill the observer thread — every
        # later watch would leak its span/semaphore/counter silently
        while True:
            try:
                if not self._drain_once():
                    return
            except Exception:
                pass

    def _drain_once(self):
        """Settle and deliver one backlog batch; False once closed and
        drained.  Each callback is guarded individually so one bad
        callback cannot skip its batch siblings."""
        with self._cv:
            while not self._backlog and not self._closed:
                self._cv.wait()
            if not self._backlog:
                return False
            batch, self._backlog = self._backlog, []
        self._settle([arrays for arrays, _ in batch])
        for _, callback in batch:
            try:
                callback()
            except Exception:  # noqa: BLE001 - siblings must still run
                pass
        return True

    def close(self, timeout=30):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)
