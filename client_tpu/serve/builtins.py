"""Built-in models for the in-process server.

These mirror the model zoo the reference's examples assume on a Triton server
(the "simple" add/sub model family, identity, sequence and decoupled models —
see reference src/python/examples/*), so the examples and tests here run
hermetically. JAX/TPU models live in client_tpu.serve.models.
"""

import time

import numpy as np

from client_tpu.serve.model_runtime import Model, TensorSpec


def simple_model():
    """INT32 add/sub: OUTPUT0 = INPUT0 + INPUT1, OUTPUT1 = INPUT0 - INPUT1.

    Shape parity with the Triton qa 'simple' model ([1,16], batchable).
    """

    def fn(inputs, params, ctx):
        a, b = inputs["INPUT0"], inputs["INPUT1"]
        return {"OUTPUT0": a + b, "OUTPUT1": a - b}

    return Model(
        "simple",
        inputs=[
            TensorSpec("INPUT0", "INT32", [-1, 16]),
            TensorSpec("INPUT1", "INT32", [-1, 16]),
        ],
        outputs=[
            TensorSpec("OUTPUT0", "INT32", [-1, 16]),
            TensorSpec("OUTPUT1", "INT32", [-1, 16]),
        ],
        fn=fn,
        max_batch_size=8,
    )


def simple_string_model():
    """BYTES add/sub on string-encoded integers (parity: simple_string examples)."""

    def fn(inputs, params, ctx):
        a = np.array([int(x) for x in inputs["INPUT0"].flatten()])
        b = np.array([int(x) for x in inputs["INPUT1"].flatten()])
        shape = inputs["INPUT0"].shape
        enc = lambda arr: np.array(
            [str(int(v)).encode() for v in arr], dtype=np.object_
        ).reshape(shape)
        return {"OUTPUT0": enc(a + b), "OUTPUT1": enc(a - b)}

    return Model(
        "simple_string",
        inputs=[
            TensorSpec("INPUT0", "BYTES", [-1, 16]),
            TensorSpec("INPUT1", "BYTES", [-1, 16]),
        ],
        outputs=[
            TensorSpec("OUTPUT0", "BYTES", [-1, 16]),
            TensorSpec("OUTPUT1", "BYTES", [-1, 16]),
        ],
        fn=fn,
        max_batch_size=8,
    )


def identity_model(name="identity", datatype="FP32"):
    """Echo INPUT0 -> OUTPUT0 unchanged (any shape)."""

    def fn(inputs, params, ctx):
        return {"OUTPUT0": inputs["INPUT0"]}

    return Model(
        name,
        inputs=[TensorSpec("INPUT0", datatype, [-1])],
        outputs=[TensorSpec("OUTPUT0", datatype, [-1])],
        fn=fn,
    )


def slow_identity_model(delay_s=0.05):
    """Identity with a fixed server-side delay — the timeout-behavior test
    model (the reference ships delay models for the same purpose)."""

    def fn(inputs, params, ctx):
        time.sleep(delay_s)
        return {"OUTPUT0": inputs["INPUT0"]}

    return Model(
        "slow_identity",
        inputs=[TensorSpec("INPUT0", "INT32", [-1])],
        outputs=[TensorSpec("OUTPUT0", "INT32", [-1])],
        fn=fn,
    )


def sequence_model():
    """Stateful accumulator (parity: the simple_sequence examples' model).

    Per sequence: OUTPUT = running sum of INPUT values; on sequence_start the
    accumulator resets to the input value.
    """

    def fn(inputs, params, ctx):
        value = inputs["INPUT"]
        if ctx is None:
            return {"OUTPUT": value}
        if params.get("sequence_start") or "acc" not in ctx.state:
            ctx.state["acc"] = np.zeros_like(value)
        ctx.state["acc"] = ctx.state["acc"] + value
        return {"OUTPUT": ctx.state["acc"].copy()}

    return Model(
        "simple_sequence",
        inputs=[TensorSpec("INPUT", "INT32", [1])],
        outputs=[TensorSpec("OUTPUT", "INT32", [1])],
        fn=fn,
        stateful=True,
    )


def decoupled_model():
    """Decoupled streamer: for input [n, delay?] yields n responses 0..n-1.

    Mirrors the shape of Triton's repeat/decoupled sample models used for LLM
    token streaming tests.
    """

    def fn(inputs, params, ctx):
        n = int(np.asarray(inputs["IN"]).flatten()[0])
        for i in range(n):
            yield {"OUT": np.array([i], dtype=np.int32)}

    return Model(
        "repeat_int32",
        inputs=[TensorSpec("IN", "INT32", [1])],
        outputs=[TensorSpec("OUT", "INT32", [1])],
        fn=fn,
        decoupled=True,
    )


def classification_model():
    """Softmax-ish scores with labels, for the classification extension."""
    labels = ["cat", "dog", "bird", "fish"]

    def fn(inputs, params, ctx):
        x = inputs["INPUT0"].astype(np.float32)
        e = np.exp(x - x.max(axis=-1, keepdims=True))
        return {"OUTPUT0": e / e.sum(axis=-1, keepdims=True)}

    return Model(
        "classifier",
        inputs=[TensorSpec("INPUT0", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUTPUT0", "FP32", [-1, 4], labels=labels)],
        fn=fn,
    )


def ensemble_model():
    """Config-driven ensemble chaining simple -> identity_int32 (the
    reference's ensemble_add_sub pattern: ensemble_scheduling steps with
    input_map/output_map, composing models keep their own statistics)."""
    return Model(
        "simple_ensemble",
        inputs=[
            TensorSpec("INPUT0", "INT32", [-1, 16]),
            TensorSpec("INPUT1", "INT32", [-1, 16]),
        ],
        outputs=[
            TensorSpec("OUTPUT0", "INT32", [-1, 16]),
            TensorSpec("OUTPUT1", "INT32", [-1, 16]),
        ],
        fn=None,  # the engine's ensemble scheduler runs the steps
        platform="ensemble",
        ensemble_steps=[
            {
                "model_name": "simple",
                "input_map": {"INPUT0": "INPUT0", "INPUT1": "INPUT1"},
                "output_map": {"OUTPUT0": "sum", "OUTPUT1": "diff"},
            },
            {
                "model_name": "identity_int32",
                "input_map": {"INPUT0": "sum"},
                "output_map": {"OUTPUT0": "OUTPUT0"},
            },
            {
                "model_name": "identity_int32",
                "input_map": {"INPUT0": "diff"},
                "output_map": {"OUTPUT0": "OUTPUT1"},
            },
        ],
    )


def default_models():
    models = [
        simple_model(),
        simple_string_model(),
        identity_model(),
        identity_model("identity_bytes", "BYTES"),
        identity_model("identity_int32", "INT32"),
        identity_model("identity_int8", "INT8"),
        slow_identity_model(),
        sequence_model(),
        decoupled_model(),
        classification_model(),
        ensemble_model(),
    ]
    # vision pipeline (preprocess -> resnet backbone -> postprocess DAG):
    # the hermetic tiny variant — jax-backed composing models whose
    # intermediates stay device-resident between steps (serve/pipeline.py).
    # Parameters initialize on first forward, so building the set stays
    # cheap; a jax-less install keeps the numpy-only builtin set instead
    # of failing at startup (the module above imports jax).
    try:
        from client_tpu.serve.models.vision import vision_pipeline_models
    except ImportError:
        return models
    models.extend(vision_pipeline_models())
    return models
