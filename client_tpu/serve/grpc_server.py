"""KServe-v2 gRPC frontend for the in-process inference engine.

Registers generic method handlers from the client_tpu._grpc_service table (no
grpcio-tools). Bridges protobuf requests to the engine's JSON-dict execution
form, including bidirectional ModelStreamInfer with decoupled (N-response)
model support — the transport the LLM token-streaming configs use.
"""

import time
from concurrent import futures

import grpc
from google.protobuf import json_format

from client_tpu._grpc_service import METHODS, SERVICE
from client_tpu._proto import inference_pb2 as pb
from client_tpu._proto import model_config_pb2 as mc
from client_tpu.serve import frontdoor, model_runtime
from client_tpu.utils import InferenceServerException, to_wire_bytes
from client_tpu._infer_types import _np_from_json_data

_STATUS_MAP = {
    "400": grpc.StatusCode.INVALID_ARGUMENT,
    "404": grpc.StatusCode.NOT_FOUND,
    # retryable overload/drain shedding: UNAVAILABLE is the status gRPC
    # clients (incl. client_tpu.resilience retry policies) retry on
    "429": grpc.StatusCode.RESOURCE_EXHAUSTED,
    "503": grpc.StatusCode.UNAVAILABLE,
    "500": grpc.StatusCode.INTERNAL,
    "501": grpc.StatusCode.UNIMPLEMENTED,
}


def _abort(context, exc):
    code = grpc.StatusCode.INVALID_ARGUMENT
    if isinstance(exc, InferenceServerException) and exc.status():
        code = _STATUS_MAP.get(exc.status(), grpc.StatusCode.UNKNOWN)
    msg = exc.message() if isinstance(exc, InferenceServerException) else str(exc)
    # QoS/overload sheds carry a backoff hint in trailing metadata (the
    # gRPC spelling of the HTTP Retry-After header)
    hint = getattr(exc, "retry_after_s", None)
    if hint:
        context.set_trailing_metadata((("retry-after", f"{float(hint):.3f}"),))
    context.abort(code, msg)


def _tenant_of(context):
    """Tenant identity from the request metadata (serve/frontdoor.py)."""
    for key, value in context.invocation_metadata() or ():
        if key == frontdoor.TENANT_HEADER:
            return value
    return ""


def _param_value(param):
    which = param.WhichOneof("parameter_choice")
    return getattr(param, which) if which else None


def _request_to_dict(request):
    """ModelInferRequest proto -> (engine request dict, binary buffers).

    The binary section is handed to the engine as the *list* of per-tensor
    proto buffers, untouched — the engine wraps each in a zero-copy numpy
    view (np.frombuffer on the proto-owned bytes), so wire tensors are never
    copied between the transport and the model (the hot-path analog of the
    reference's zero-copy serialization, grpc_client.cc:1373-1411).
    """
    req = {"id": request.id}
    params = {k: _param_value(v) for k, v in request.parameters.items()}
    req["parameters"] = params

    raw_cursor = 0
    binary_parts = []
    inputs = []
    for tensor in request.inputs:
        entry = {
            "name": tensor.name,
            "datatype": tensor.datatype,
            "shape": list(tensor.shape),
        }
        tparams = {k: _param_value(v) for k, v in tensor.parameters.items()}
        if "shared_memory_region" in tparams:
            entry["parameters"] = tparams
        elif tensor.HasField("contents"):
            # Typed repeated-field contents: normalize to wire bytes so the
            # engine has a single decode path.
            arr = _contents_to_array(tensor)
            raw = to_wire_bytes(arr, tensor.datatype)
            entry["parameters"] = {"binary_data_size": len(raw)}
            binary_parts.append(raw)
        else:
            if raw_cursor >= len(request.raw_input_contents):
                raise InferenceServerException(
                    f"input '{tensor.name}' has no data", status="400"
                )
            raw = request.raw_input_contents[raw_cursor]
            raw_cursor += 1
            entry["parameters"] = {"binary_data_size": len(raw)}
            binary_parts.append(raw)
        inputs.append(entry)
    req["inputs"] = inputs

    if request.outputs:
        outputs = []
        for out in request.outputs:
            oparams = {k: _param_value(v) for k, v in out.parameters.items()}
            if "shared_memory_region" not in oparams:
                oparams["binary_data"] = True
            oparams.pop("binary_data_size", None)
            outputs.append({"name": out.name, "parameters": oparams})
        req["outputs"] = outputs
    else:
        params["binary_data_output"] = True
    return req, binary_parts


def _contents_to_array(tensor):
    from client_tpu._grpc_infer import _CONTENTS_FIELD

    field = _CONTENTS_FIELD.get(tensor.datatype)
    if field is None:
        raise InferenceServerException(
            f"unsupported datatype {tensor.datatype}", status="400"
        )
    values = list(getattr(tensor.contents, field))
    if tensor.datatype == "BYTES":
        return _np_from_json_data(values, "BYTES", list(tensor.shape))
    return _np_from_json_data(values, tensor.datatype, list(tensor.shape))


def _set_infer_param(proto_params, key, value):
    """Python value -> InferParameter oneof (bool checked before int:
    bool is an int subclass)."""
    if isinstance(value, bool):
        proto_params[key].bool_param = value
    elif isinstance(value, int):
        proto_params[key].int64_param = value
    else:
        proto_params[key].string_param = str(value)


def _dict_to_response(model_name, model_version, response_json, blobs):
    """Engine response dict + blobs -> ModelInferResponse proto."""
    response = pb.ModelInferResponse(
        model_name=response_json.get("model_name", model_name),
        model_version=response_json.get("model_version", model_version),
        id=response_json.get("id", ""),
    )
    # response-level parameters (decoupled final markers etc.)
    for key, value in (response_json.get("parameters", {}) or {}).items():
        _set_infer_param(response.parameters, key, value)
    # raw_output_contents must align positionally with non-shm outputs, so
    # interleave binary blobs and any JSON-data fallbacks in output order.
    raws = []
    blob_cursor = 0
    for entry in response_json.get("outputs", []):
        out = response.outputs.add()
        out.name = entry["name"]
        out.datatype = entry["datatype"]
        out.shape.extend(entry["shape"])
        eparams = entry.get("parameters", {}) or {}
        for key, value in eparams.items():
            if key == "binary_data_size":
                continue
            _set_infer_param(out.parameters, key, value)
        if "binary_data_size" in eparams:
            raws.append(blobs[blob_cursor])
            blob_cursor += 1
        elif "data" in entry:
            arr = _np_from_json_data(
                entry["data"], entry["datatype"], entry["shape"]
            )
            raws.append(to_wire_bytes(arr, entry["datatype"]))
    response.raw_output_contents.extend(raws)
    return response


class _Handlers:
    def __init__(self, engine, verbose=False):
        self.engine = engine
        self.verbose = verbose

    # health ---------------------------------------------------------------

    def ServerLive(self, request, context):
        return pb.ServerLiveResponse(live=True)

    def ServerReady(self, request, context):
        # drain() flips readiness false so load balancers stop routing here
        return pb.ServerReadyResponse(ready=self.engine.ready())

    def ModelReady(self, request, context):
        return pb.ModelReadyResponse(
            ready=self.engine.model_ready(request.name, request.version)
        )

    # metadata ---------------------------------------------------------------

    def ServerMetadata(self, request, context):
        return pb.ServerMetadataResponse(
            name=model_runtime.SERVER_NAME,
            version=model_runtime.SERVER_VERSION,
            extensions=model_runtime.SERVER_EXTENSIONS,
        )

    def ModelMetadata(self, request, context):
        try:
            model = self.engine.get_model(request.name, request.version)
        except InferenceServerException as e:
            _abort(context, e)
        meta = model.metadata()
        response = pb.ModelMetadataResponse(
            name=meta["name"], versions=meta["versions"], platform=meta["platform"]
        )
        for t in meta["inputs"]:
            tm = response.inputs.add()
            tm.name, tm.datatype = t["name"], t["datatype"]
            tm.shape.extend(t["shape"])
        for t in meta["outputs"]:
            tm = response.outputs.add()
            tm.name, tm.datatype = t["name"], t["datatype"]
            tm.shape.extend(t["shape"])
        return response

    def ModelConfig(self, request, context):
        try:
            model = self.engine.get_model(request.name, request.version)
        except InferenceServerException as e:
            _abort(context, e)
        config = json_format.ParseDict(
            model.config(), mc.ModelConfig(), ignore_unknown_fields=True
        )
        return pb.ModelConfigResponse(config=config)

    # repository -------------------------------------------------------------

    def RepositoryIndex(self, request, context):
        response = pb.RepositoryIndexResponse()
        for entry in self.engine.repository_index(request.ready):
            m = response.models.add()
            m.name, m.version = entry["name"], entry["version"]
            m.state, m.reason = entry["state"], entry["reason"]
        return response

    def RepositoryModelLoad(self, request, context):
        import json as _json

        config = None
        files = {}
        for key, param in request.parameters.items():
            if key == "config":
                config = _json.loads(param.string_param)
            elif param.WhichOneof("parameter_choice") == "bytes_param":
                files[key] = param.bytes_param
        try:
            self.engine.load_model(
                request.model_name, config_override=config, files=files or None
            )
        except InferenceServerException as e:
            _abort(context, e)
        return pb.RepositoryModelLoadResponse()

    def RepositoryModelUnload(self, request, context):
        try:
            self.engine.unload_model(request.model_name)
        except InferenceServerException as e:
            _abort(context, e)
        return pb.RepositoryModelUnloadResponse()

    # statistics / trace / log -----------------------------------------------

    def ModelStatistics(self, request, context):
        try:
            stats = self.engine.statistics(request.name, request.version)
        except InferenceServerException as e:
            _abort(context, e)
        response = pb.ModelStatisticsResponse()
        for entry in stats:
            response.model_stats.append(
                json_format.ParseDict(entry, pb.ModelStatistics())
            )
        return response

    def TraceSetting(self, request, context):
        settings = self.engine.trace_settings
        if request.settings:
            updates = {}
            for key, value in request.settings.items():
                values = list(value.value)
                if not values:
                    continue
                updates[key] = values if key == "trace_level" else values[0]
            try:
                # same normalization point as the HTTP verb, so settings
                # round-trip identically over both protocols
                settings = self.engine.update_trace_settings(updates)
            except InferenceServerException as e:
                _abort(context, e)
        response = pb.TraceSettingResponse()
        for key, value in settings.items():
            values = value if isinstance(value, list) else [str(value)]
            response.settings[key].value.extend(values)
        return response

    def LogSettings(self, request, context):
        settings = self.engine.log_settings
        if request.settings:
            for key, value in request.settings.items():
                which = value.WhichOneof("parameter_choice")
                if which:
                    settings[key] = getattr(value, which)
        response = pb.LogSettingsResponse()
        for key, value in settings.items():
            if isinstance(value, bool):
                response.settings[key].bool_param = value
            elif isinstance(value, int):
                response.settings[key].uint32_param = value
            else:
                response.settings[key].string_param = str(value)
        return response

    # shared memory ----------------------------------------------------------

    def SystemSharedMemoryStatus(self, request, context):
        try:
            regions = self.engine.shm.system_status(request.name or None)
        except InferenceServerException as e:
            _abort(context, e)
        response = pb.SystemSharedMemoryStatusResponse()
        for name, r in regions.items():
            response.regions[name].name = name
            response.regions[name].key = r["key"]
            response.regions[name].offset = r["offset"]
            response.regions[name].byte_size = r["byte_size"]
        return response

    def SystemSharedMemoryRegister(self, request, context):
        try:
            self.engine.shm.register_system(
                request.name, request.key, request.offset, request.byte_size
            )
        except InferenceServerException as e:
            _abort(context, e)
        return pb.SystemSharedMemoryRegisterResponse()

    def SystemSharedMemoryUnregister(self, request, context):
        self.engine.shm.unregister_system(request.name or None)
        return pb.SystemSharedMemoryUnregisterResponse()

    def CudaSharedMemoryStatus(self, request, context):
        return pb.CudaSharedMemoryStatusResponse()

    def CudaSharedMemoryRegister(self, request, context):
        context.abort(
            grpc.StatusCode.INVALID_ARGUMENT,
            "CUDA shared memory is not supported by this server "
            "(use TpuSharedMemoryRegister)",
        )

    def CudaSharedMemoryUnregister(self, request, context):
        return pb.CudaSharedMemoryUnregisterResponse()

    def TpuSharedMemoryStatus(self, request, context):
        try:
            regions = self.engine.shm.tpu_status(request.name or None)
        except InferenceServerException as e:
            _abort(context, e)
        response = pb.TpuSharedMemoryStatusResponse()
        for name, r in regions.items():
            response.regions[name].name = name
            response.regions[name].device_id = r["device_id"]
            response.regions[name].byte_size = r["byte_size"]
        return response

    def TpuSharedMemoryRegister(self, request, context):
        try:
            self.engine.shm.register_tpu(
                request.name, request.raw_handle, request.device_id, request.byte_size
            )
        except InferenceServerException as e:
            _abort(context, e)
        return pb.TpuSharedMemoryRegisterResponse()

    def TpuSharedMemoryUnregister(self, request, context):
        self.engine.shm.unregister_tpu(request.name or None)
        return pb.TpuSharedMemoryUnregisterResponse()

    # inference --------------------------------------------------------------

    def _sample_trace(self, request, context):
        """A RequestTrace for this RPC (or None), joined to the client's
        trace id via the traceparent metadata entry when present."""
        traceparent = None
        for key, value in context.invocation_metadata() or ():
            if key == "traceparent":
                traceparent = value
                break
        return self.engine.tracer.sample(
            traceparent, model_name=request.model_name,
            model_version=request.model_version, protocol="grpc",
        )

    def ModelInfer(self, request, context):
        trace = self._sample_trace(request, context)
        if trace is not None:
            trace.event("REQUEST_START")
        # wire-path profiling (serve/prof.py): proto-decode / execute-
        # wait / proto-encode splits committed as one "grpc" tick
        ptick = self.engine.wire_prof.start_tick("grpc")
        try:
            t_mark = time.perf_counter()
            req, binary = _request_to_dict(request)
            ptick.add("deserialize", time.perf_counter() - t_mark)
            t_mark = time.perf_counter()
            result = self.engine.execute(
                request.model_name, request.model_version, req, binary,
                trace=trace, tenant=_tenant_of(context),
            )
            ptick.add("wait", time.perf_counter() - t_mark)
            if not isinstance(result, tuple):  # list/generator = decoupled
                if hasattr(result, "close"):
                    result.close()  # release its in-flight admission slot
                raise InferenceServerException(
                    f"model '{request.model_name}' is decoupled; use "
                    "ModelStreamInfer",
                    status="400",
                )
            response_json, blobs = result
            t_mark = time.perf_counter()
            response = _dict_to_response(
                request.model_name, request.model_version, response_json, blobs
            )
            ptick.add("serialize", time.perf_counter() - t_mark)
            if trace is not None:
                trace.event("RESPONSE_SENT")
            return response
        except InferenceServerException as e:
            if trace is not None:
                trace.error = str(e)
            _abort(context, e)
        finally:
            self.engine.wire_prof.finish(ptick)
            if trace is not None:
                self.engine.tracer.complete(trace)

    def ModelStreamInfer(self, request_iterator, context):
        tenant = _tenant_of(context)  # one identity per stream connection
        for request in request_iterator:
            trace = self._sample_trace(request, context)
            if trace is not None:
                trace.event("REQUEST_START")
            try:
                req, binary = _request_to_dict(request)
                result = self.engine.execute(
                    request.model_name, request.model_version, req, binary,
                    trace=trace, tenant=tenant,
                )
                # a decoupled result streams lazily (generator): each
                # response reaches the wire as the model produces it
                responses = [result] if isinstance(result, tuple) else result
                for response_json, blobs in responses:
                    yield pb.ModelStreamInferResponse(
                        infer_response=_dict_to_response(
                            request.model_name,
                            request.model_version,
                            response_json,
                            blobs,
                        )
                    )
                if trace is not None:
                    trace.event("RESPONSE_SENT")
            except InferenceServerException as e:
                # ModelStreamInferResponse carries only a message string, so
                # the status rides as a "[<status>] " prefix (str(e) form);
                # the client strips it back into InferenceServerException.status
                if trace is not None:
                    trace.error = str(e)
                err = pb.ModelStreamInferResponse(error_message=str(e))
                err.infer_response.id = request.id
                yield err
            except Exception as e:  # pragma: no cover - defensive
                if trace is not None:
                    trace.error = str(e)
                yield pb.ModelStreamInferResponse(error_message=str(e))
            finally:
                if trace is not None:
                    self.engine.tracer.complete(trace)


class GrpcFrontend:
    """grpc.server bound to an InferenceEngine via generic method handlers."""

    def __init__(self, engine, host="127.0.0.1", port=0, verbose=False, max_workers=96):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="client_tpu-grpc"
            ),
            options=[
                ("grpc.max_send_message_length", 2**31 - 1),
                ("grpc.max_receive_message_length", 2**31 - 1),
            ],
        )
        handlers_obj = _Handlers(engine, verbose)
        method_handlers = {}
        for name, (req_cls, resp_cls, cstream, sstream) in METHODS.items():
            fn = getattr(handlers_obj, name)
            kwargs = {
                "request_deserializer": req_cls.FromString,
                "response_serializer": resp_cls.SerializeToString,
            }
            if cstream and sstream:
                handler = grpc.stream_stream_rpc_method_handler(fn, **kwargs)
            elif sstream:
                handler = grpc.unary_stream_rpc_method_handler(fn, **kwargs)
            elif cstream:
                handler = grpc.stream_unary_rpc_method_handler(fn, **kwargs)
            else:
                handler = grpc.unary_unary_rpc_method_handler(fn, **kwargs)
            method_handlers[name] = handler
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, method_handlers),)
        )
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        self._host = host

    @property
    def address(self):
        return f"{self._host}:{self._port}"

    def start(self):
        self._server.start()
        return self

    def stop(self):
        self._server.stop(grace=2)
