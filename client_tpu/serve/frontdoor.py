"""Multi-tenant front door: response cache, request coalescing, tenant QoS.

"Millions of users" traffic is not uniform — it has hot keys (the same
request sent by thousands of clients at once) and unfair tenants (one
integration bug floods the fleet).  The engine-level shedding from the
resilience layer treats all of that as one FIFO, which degrades every
user equally; this module is the part that degrades *selectively*:

- :class:`ResponseCache` — a content-addressed inference response cache
  (exact match on model + version + input digest; the server-side analog
  of the reference ModelParser's ``response cache`` flag, whose hit/miss
  durations surface in perf stats).  Bounded LRU with optional TTL;
  hit/miss/eviction/bytes metrics.
- :class:`Coalescer` — in-flight request coalescing: N identical
  concurrent requests collapse to ONE model dispatch whose result fans
  out to all N waiters.  A hot-key storm costs one TPU dispatch instead
  of N.
- :class:`TenantQoS` — per-tenant admission control layered on the
  engine's global shedding: priority-class weights (consumed by the
  dynamic batcher's weighted-fair queue), per-tenant in-flight caps and
  token-bucket rate quotas.  Violations are rejected with a *retryable*
  429 carrying a ``Retry-After`` hint, which the client-side
  ``client_tpu.resilience.RetryPolicy`` already honors — a well-behaved
  flooder backs off instead of erroring.

Tenant identity arrives on the wire as the ``x-tenant-id`` HTTP header /
gRPC metadata key (:data:`TENANT_HEADER`); requests without it share the
default tenant ``""``.
"""

import hashlib
import threading
import time
from collections import OrderedDict

from client_tpu.utils import TENANT_HEADER, InferenceServerException

__all__ = [
    "TENANT_HEADER",
    "ResponseCache",
    "Coalescer",
    "TenantQoS",
    "request_digest",
]


def request_digest(model_name, model_version, request, binary_section):
    """Content digest of one inference request, or None when uncacheable.

    Exact-match semantics: two requests share a digest iff they name the
    same model+version and carry byte-identical inputs, the same requested
    outputs (rendering flags included — they change the response body),
    and the same request parameters.  The request ``id`` is excluded (it
    is caller identity, not content — the hit path re-stamps it) and so is
    tenant identity: a hot key is hot *across* tenants.

    Uncacheable shapes return None:
    - sequence requests (``sequence_id``): the response depends on server
      state, not just the request bytes;
    - shared-memory inputs or outputs: the payload lives in a region this
      process may not re-read later (inputs), or the response's side
      effect is a region write that must happen per request (outputs).
    """
    params = request.get("parameters") or {}
    if params.get("sequence_id"):
        return None
    h = hashlib.sha256()
    h.update(model_name.encode("utf-8"))
    h.update(b"\x00")
    h.update(str(model_version).encode("utf-8"))
    for entry in request.get("inputs") or []:
        eparams = entry.get("parameters") or {}
        if "shared_memory_region" in eparams:
            return None
        h.update(b"\x01")
        h.update(str(entry.get("name", "")).encode("utf-8"))
        h.update(str(entry.get("datatype", "")).encode("utf-8"))
        h.update(repr(list(entry.get("shape") or [])).encode("utf-8"))
        if "data" in entry:
            h.update(repr(entry["data"]).encode("utf-8"))
        h.update(repr(sorted(eparams.items())).encode("utf-8"))
    for out in request.get("outputs") or []:
        oparams = out.get("parameters") or {}
        if "shared_memory_region" in oparams:
            return None
        h.update(b"\x02")
        h.update(str(out.get("name", "")).encode("utf-8"))
        h.update(repr(sorted(oparams.items())).encode("utf-8"))
    h.update(b"\x03")
    h.update(repr(sorted(params.items())).encode("utf-8"))
    h.update(b"\x04")
    if binary_section:
        if isinstance(binary_section, (list, tuple)):
            for part in binary_section:
                h.update(bytes(part))
                h.update(b"\x05")
        else:
            h.update(bytes(binary_section))
    return h.hexdigest()


def _response_nbytes(response_json, blobs):
    """Approximate retained bytes of one cached (response, blobs) value."""
    n = sum(len(b) for b in blobs)
    for out in response_json.get("outputs") or []:
        data = out.get("data")
        if data is not None:
            n += 8 * len(data)  # JSON-rendered scalars, rough host cost
    return n + 256  # dict/key overhead floor so empty entries still count


class ResponseCache:
    """Bounded content-addressed LRU cache of rendered responses.

    Values are ``(response_json, blobs)`` exactly as the engine returns
    them, stored WITHOUT the request ``id`` (the hit path stamps the
    requester's own).  Eviction is LRU by entry count and by retained
    bytes; ``ttl_s`` (optional) expires entries at read time.

    Metrics (when built with a :class:`client_tpu.serve.metrics.Registry`):
    ``ctpu_cache_hits_total`` / ``ctpu_cache_misses_total`` /
    ``ctpu_cache_evictions_total{reason}`` counters and the
    ``ctpu_cache_entries`` / ``ctpu_cache_bytes`` gauges.
    """

    def __init__(self, max_entries=1024, max_bytes=64 << 20, ttl_s=None,
                 registry=None):
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.ttl_s = ttl_s
        self.registry = registry
        self._lock = threading.Lock()
        # key -> (value, nbytes, stored_at, ttl_s) — ttl_s None means
        # the cache-wide default applies
        self._entries = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def stats(self):
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "entries": len(self._entries),
                "bytes": self._bytes,
            }

    def _inc(self, name, labels=None):
        if self.registry is not None:
            self.registry.inc(name, labels, help_=_CACHE_HELP[name])

    def _gauges_locked(self):
        if self.registry is not None:
            self.registry.set(
                "ctpu_cache_entries", None, len(self._entries),
                help_=_CACHE_HELP["ctpu_cache_entries"],
            )
            self.registry.set(
                "ctpu_cache_bytes", None, self._bytes,
                help_=_CACHE_HELP["ctpu_cache_bytes"],
            )

    def get(self, key):
        """Cached value for *key* or None; counts the hit/miss."""
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                ttl = entry[3] if entry[3] is not None else self.ttl_s
                if ttl is not None and now - entry[2] > ttl:
                    self._entries.pop(key)
                    self._bytes -= entry[1]
                    self.evictions += 1
                    self._gauges_locked()
                    entry = None
                    self._inc("ctpu_cache_evictions_total",
                              {"reason": "ttl"})
            if entry is None:
                self.misses += 1
                self._inc("ctpu_cache_misses_total")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self._inc("ctpu_cache_hits_total")
            return entry[0]

    def put(self, key, response_json, blobs, ttl_s=None):
        """Insert one rendered response (no-op for values that alone exceed
        the byte bound — caching them would evict the whole working set).

        ``ttl_s`` overrides the cache-wide TTL for THIS entry — the
        per-model ``response_cache`` config block's freshness hint (a
        weather model's answers go stale in seconds, an embedding
        model's never do)."""
        nbytes = _response_nbytes(response_json, blobs)
        if nbytes > self.max_bytes:
            return
        value = (response_json, blobs)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes, time.monotonic(), ttl_s)
            self._bytes += nbytes
            while (
                len(self._entries) > self.max_entries
                or self._bytes > self.max_bytes
            ):
                _, (_, evicted_bytes, _, _) = self._entries.popitem(
                    last=False
                )
                self._bytes -= evicted_bytes
                self.evictions += 1
                self._inc("ctpu_cache_evictions_total", {"reason": "lru"})
            self._gauges_locked()

    def peek(self, key):
        """Read *key* WITHOUT touching hit/miss counters or LRU order —
        the fleet peer-serving path: a peer's lookup must not skew this
        replica's own hit-rate accounting (its miss already counted on
        the replica that asked) nor keep entries hot that only remote
        traffic touches.  TTL still applies (a stale entry is stale for
        peers too), but expiry is left to the owning ``get`` path."""
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            ttl = entry[3] if entry[3] is not None else self.ttl_s
            if ttl is not None and now - entry[2] > ttl:
                return None
            return entry[0]

    def keys(self):
        """Digest-key snapshot (the routing-gossip summary source)."""
        with self._lock:
            return list(self._entries)

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._gauges_locked()


_CACHE_HELP = {
    "ctpu_cache_hits_total": "Response-cache hits",
    "ctpu_cache_misses_total": "Response-cache misses",
    "ctpu_cache_evictions_total": "Response-cache evictions (lru/ttl)",
    "ctpu_cache_entries": "Response-cache live entry count",
    "ctpu_cache_bytes": "Response-cache retained bytes",
}


class _Flight:
    """One in-flight dispatch identical concurrent requests attach to."""

    __slots__ = ("event", "result", "error", "retry", "followers")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error = None
        # leader was rejected by ITS OWN tenant's admission (429): that
        # error is tenant-scoped, not content-scoped — followers must
        # re-contend under their own quotas instead of inheriting it
        self.retry = False
        self.followers = 0


class Coalescer:
    """Collapse identical concurrent requests into one dispatch.

    The leader (first arrival for a key) executes; followers block until
    the leader publishes and receive the same rendered result (the hit
    path stamps each follower's own request id).  The leader ALWAYS
    publishes — success or error — in a ``finally``, so followers can
    wait without a timeout.  An error fans out to the followers too:
    a byte-identical request would have failed identically, and retrying
    it N times is exactly the herd coalescing exists to prevent.

    Metrics: ``ctpu_coalesced_requests_total`` (followers served without
    a dispatch) and the high-watermark gauge ``ctpu_coalesce_depth_max``
    (largest N collapsed into one dispatch).
    """

    def __init__(self, registry=None):
        self.registry = registry
        self._lock = threading.Lock()
        self._flights = {}
        self.coalesced = 0
        self.depth_max = 0

    def join(self, key):
        """Returns ``(is_leader, flight)``; leaders must complete the
        flight via :meth:`publish` / :meth:`fail` (once)."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                return True, flight
            flight.followers += 1
            self.coalesced += 1
            depth = flight.followers + 1  # leader included
            if depth > self.depth_max:
                self.depth_max = depth
                if self.registry is not None:
                    self.registry.set(
                        "ctpu_coalesce_depth_max", None, depth,
                        help_="Largest request count collapsed into one "
                              "dispatch",
                    )
            if self.registry is not None:
                self.registry.inc(
                    "ctpu_coalesced_requests_total",
                    help_="Requests served from a peer's in-flight dispatch",
                )
            return False, flight

    def publish(self, key, flight, result):
        with self._lock:
            self._flights.pop(key, None)
        flight.result = result
        flight.event.set()

    def fail(self, key, flight, error):
        with self._lock:
            self._flights.pop(key, None)
        flight.error = error
        flight.event.set()

    def retry_followers(self, key, flight):
        """Release the followers to re-contend (one becomes the next
        leader under its OWN tenant's admission) — for leader failures
        that are tenant-scoped, not content-scoped."""
        with self._lock:
            self._flights.pop(key, None)
        flight.retry = True
        flight.event.set()


class _TokenBucket:
    """Classic token bucket; ``take()`` returns 0.0 on admit or the
    seconds until a token will exist (the Retry-After hint)."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate_per_s, burst):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self.tokens = self.burst
        self.stamp = time.monotonic()

    def take(self, now):
        # clamp: the caller's `now` can predate this bucket's creation
        # stamp (captured before the state was lazily built); a negative
        # elapsed must not drain the bucket below its real level
        self.tokens = min(
            self.burst,
            self.tokens + max(now - self.stamp, 0.0) * self.rate,
        )
        self.stamp = max(now, self.stamp)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate if self.rate > 0 else 1.0


class _TenantState:
    __slots__ = ("inflight", "bucket", "requests", "shed", "gossip_delta")

    def __init__(self):
        self.inflight = 0
        self.bucket = None
        self.requests = 0
        self.shed = 0
        # admissions since the last fleet-gossip collection: what peers
        # drain from THEIR buckets so a flooder spraying N replicas
        # converges on ~1x its quota fleet-wide, not N x
        self.gossip_delta = 0


class TenantQoS:
    """Per-tenant admission control + priority-class weights.

    Parameters
    ----------
    default_weight : fair-queue weight for tenants without an explicit
        class entry (the dynamic batcher shares batch capacity
        proportionally to weight).
    default_max_inflight : per-tenant concurrent-request cap (None =
        uncapped).  The cap is what keeps a flooder from occupying every
        engine execution slot.
    default_rate_per_s / default_burst : per-tenant token-bucket quota
        (None = unmetered).  Burst defaults to 2x the rate.
    tenants : {name: {"weight", "max_inflight", "rate_per_s", "burst"}}
        per-tenant overrides (priority classes are expressed as weights:
        gold=8.0, bronze=1.0).
    registry : optional metrics Registry for the per-tenant series.

    :meth:`admit` raises a retryable 429 (with ``retry_after_s`` — the
    HTTP frontend renders it as the ``Retry-After`` header) when a quota
    or cap is exceeded; on success it returns a release callable that
    MUST run when the request finishes (streams release at close).
    """

    def __init__(self, default_weight=1.0, default_max_inflight=None,
                 default_rate_per_s=None, default_burst=None,
                 default_lane_share=0.75, default_priority=0.0,
                 tenants=None, registry=None):
        self.default_weight = float(default_weight)
        self.default_max_inflight = default_max_inflight
        self.default_rate_per_s = default_rate_per_s
        self.default_burst = default_burst
        self.default_lane_share = default_lane_share
        self.default_priority = float(default_priority)
        self.tenants = dict(tenants or {})
        self.registry = registry
        self._lock = threading.Lock()
        self._states = {}

    # -- configuration lookups ----------------------------------------------

    def _cfg(self, tenant, key, default):
        return self.tenants.get(tenant, {}).get(key, default)

    def weight(self, tenant):
        """Fair-queue weight for *tenant* (>= a small positive floor so a
        zero/negative config cannot starve the tenant forever)."""
        w = float(self._cfg(tenant, "weight", self.default_weight))
        return max(w, 1e-3)

    def lane_share(self, tenant):
        """Max fraction of the continuous-batching DECODE LANES *tenant*
        may hold while another tenant is waiting (per-tenant ``lane_share``
        config key; None = uncapped).  Decoupled token streams bypass the
        request-level front door — one tenant's long generations would
        otherwise occupy every decode lane for minutes — so the LM engine
        enforces this at lane-admission time (work-conserving: the quota
        binds only while someone else is queued)."""
        share = self._cfg(tenant, "lane_share", self.default_lane_share)
        return None if share is None else float(share)

    def priority(self, tenant):
        """Preemption priority class of *tenant* (per-tenant ``priority``
        config key; higher outranks lower, default 0).  Weights shape how
        much service a tenant gets; priority decides who keeps their KV
        blocks when the LM engine's pool runs dry — a STRICTLY
        higher-priority waiter may swap out a lower-priority lane (the
        engine's preemption controller consumes this via the
        ``tenant_priority`` hook wired in add_model)."""
        return float(self._cfg(tenant, "priority", self.default_priority))

    def _state_locked(self, tenant):
        state = self._states.get(tenant)
        if state is None:
            state = _TenantState()
            rate = self._cfg(tenant, "rate_per_s", self.default_rate_per_s)
            if rate is not None:
                burst = self._cfg(tenant, "burst", self.default_burst)
                state.bucket = _TokenBucket(
                    rate, burst if burst is not None else max(2.0 * rate, 1.0)
                )
            self._states[tenant] = state
        return state

    # -- admission ----------------------------------------------------------

    def note(self, tenant):
        """Count one request served WITHOUT an execution dispatch (cache
        hit, coalesced follower) — those bypass the caps by design (they
        occupy no execution slot; shedding them would defeat the cache),
        but must still reconcile against the per-tenant request counters."""
        with self._lock:
            self._state_locked(tenant).requests += 1
        self._count(tenant, None)

    def admit(self, tenant):
        """Admit one dispatching request for *tenant* or raise the
        retryable 429.

        Returns a zero-arg release callable (idempotent)."""
        max_inflight = self._cfg(
            tenant, "max_inflight", self.default_max_inflight
        )
        now = time.monotonic()
        with self._lock:
            state = self._state_locked(tenant)
            state.requests += 1
            reason = None
            retry_after = 1.0
            if max_inflight is not None and state.inflight >= max_inflight:
                reason = "inflight"
            elif state.bucket is not None:
                wait = state.bucket.take(now)
                if wait > 0.0:
                    reason = "quota"
                    retry_after = wait
            if reason is None:
                state.inflight += 1
                state.gossip_delta += 1
                # gauge written under the SAME lock as the count: a
                # read-then-set outside it lets a preempted thread park
                # the gauge on a stale value (same delivery-ordering
                # discipline as pool.py's endpoint-state gauge)
                self._set_inflight_locked(tenant, state.inflight)
            else:
                state.shed += 1
        self._count(tenant, reason)
        if reason is not None:
            exc = InferenceServerException(
                f"tenant {tenant!r} exceeded its "
                f"{'in-flight cap' if reason == 'inflight' else 'rate quota'}"
                "; retry after backoff",
                status="429",
            )
            # the client RetryPolicy honors this hint (delay_for); the
            # HTTP frontend renders it as the Retry-After header
            exc.retry_after_s = max(retry_after, 0.05)
            raise exc
        released = [False]

        def release():
            with self._lock:
                if released[0]:
                    return
                released[0] = True
                state.inflight -= 1
                self._set_inflight_locked(tenant, state.inflight)

        return release

    def _count(self, tenant, reason):
        """Monotonic counters (order-insensitive: safe outside the lock)."""
        if self.registry is None:
            return
        self.registry.inc(
            "ctpu_tenant_requests_total", {"tenant": tenant},
            help_="Requests received per tenant (admitted or shed)",
        )
        if reason is not None:
            self.registry.inc(
                "ctpu_tenant_shed_total",
                {"tenant": tenant, "reason": reason},
                help_="Requests shed per tenant with a retryable 429",
            )
    def _set_inflight_locked(self, tenant, inflight):
        """Caller holds self._lock (the Registry's own lock is a leaf —
        no callbacks — so nesting it here is safe)."""
        if self.registry is not None:
            self.registry.set(
                "ctpu_tenant_inflight", {"tenant": tenant}, inflight,
                help_="Requests currently executing per tenant",
            )

    # -- fleet-wide accounting ----------------------------------------------

    def delta_counts(self):
        """{tenant: admissions since the last call} — collected by the
        fleet gossip loop and pushed to peers, then reset.  Only tenants
        with activity appear (the payload stays compact)."""
        with self._lock:
            out = {}
            for tenant, state in self._states.items():
                if state.gossip_delta:
                    out[tenant] = state.gossip_delta
                    state.gossip_delta = 0
            return out

    def absorb_remote(self, counts):
        """Drain each tenant's local token bucket by the admissions a
        PEER replica reported (fleet gossip): the rate quota becomes
        approximately fleet-wide instead of per-process, so a flooder
        cannot collect N x its quota by spraying N replicas.  Convergence
        is eventual (one gossip interval of slack); tenants without a
        bucket, or unknown here, are ignored — remote evidence must never
        fabricate local state."""
        with self._lock:
            for tenant, n in (counts or {}).items():
                state = self._states.get(tenant)
                if state is None and tenant in self.tenants:
                    # operator-configured tenant this replica just hasn't
                    # served yet: materialize its bucket so the remote
                    # consumption isn't forgotten (arbitrary gossip names
                    # stay ignored — a peer must not grow the state map)
                    state = self._state_locked(tenant)
                if state is None or state.bucket is None:
                    continue
                state.bucket.tokens = max(
                    state.bucket.tokens - float(n), 0.0
                )

    # -- introspection -------------------------------------------------------

    def snapshot(self):
        """{tenant: {"inflight", "requests", "shed"}} view."""
        with self._lock:
            return {
                t: {
                    "inflight": s.inflight,
                    "requests": s.requests,
                    "shed": s.shed,
                }
                for t, s in self._states.items()
            }
